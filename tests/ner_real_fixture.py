"""Hand-labeled real-prose NER fixture (VERDICT r2 #4).

50 sentences in news / fiction register — subordinate clauses, appositives,
quotes, entities at varied positions — NOT generated from the training
templates.  Labels are token -> NameEntityType for every entity token
(everything else is O), using ``ner_tokenize``'s tokenization.

Entity inventory spans the full TAG_SET: Person, Location, Organization,
Date, Time, Money, Percentage.  Many names are real-world entities absent
from both the gazetteers (ops/ner.py) and the training fill lists
(tools/train_ner_tagger.py); some common ones (London, France, Friday)
naturally overlap, as real text does.
"""

# (sentence, {token: entity_type})
REAL_TEXT = [
    ("When the delegates finally reached Geneva, the talks had already "
     "collapsed, and Secretary Hammond refused to comment.",
     {"Geneva": "Location", "Hammond": "Person"}),
    ("Reuters reported on Thursday that Novartis would cut nearly 8% of its "
     "workforce by December.",
     {"Reuters": "Organization", "Thursday": "Date", "Novartis":
      "Organization", "8%": "Percentage", "December": "Date"}),
    ("The old lighthouse keeper, a man named Silas Tremaine, had not left "
     "the island since 1987.",
     {"Silas": "Person", "Tremaine": "Person", "1987": "Date"}),
    ("Analysts at Barclays expect the pound to weaken against the dollar "
     "before the spring.",
     {"Barclays": "Organization"}),
    ("At 6:45am the ferry departed Piraeus, carrying mail, olives, and one "
     "very nervous accountant.",
     {"6:45am": "Time", "Piraeus": "Location"}),
    ("Their daughter Beatrice studied chemistry in Heidelberg before the "
     "war broke out.",
     {"Beatrice": "Person", "Heidelberg": "Location"}),
    ("The settlement, approved on 2019-03-22, required Consolidated Rail to "
     "pay $14M in damages.",
     {"2019-03-22": "Date", "Consolidated": "Organization",
      "Rail": "Organization", "$14M": "Money"}),
    ("Nobody in Marlow village remembered a colder January than that one.",
     {"Marlow": "Location", "January": "Date"}),
    ("Professor Okafor argued that the figures published by the World Bank "
     "understated rural poverty by at least 3.5%.",
     {"Okafor": "Person", "World": "Organization", "Bank": "Organization",
      "3.5%": "Percentage"}),
    ("It was nearly 11:30 when Inspector Valdez knocked on the door of the "
     "warehouse in Rotterdam.",
     {"11:30": "Time", "Valdez": "Person", "Rotterdam": "Location"}),
    ("Turnover at Siemens rose 6% last quarter, the company said on Monday.",
     {"Siemens": "Organization", "6%": "Percentage", "Monday": "Date"}),
    ("In the summer of 2003, two brothers from Palermo opened a bakery on "
     "Fulton Street.",
     {"2003": "Date", "Palermo": "Location", "Fulton": "Location",
      "Street": "Location"}),
    ("The committee heard testimony from Dr. Lindqvist, who had overseen "
     "the trials in Uppsala.",
     {"Lindqvist": "Person", "Uppsala": "Location"}),
    ("Freight costs climbed to $2,400 per container after the canal closed "
     "in March.",
     {"$2,400": "Money", "March": "Date"}),
    ("She sold the farm to a subsidiary of Cargill for far less than it "
     "was worth.",
     {"Cargill": "Organization"}),
    ("By 9pm the square in Krakow was empty except for the pigeons.",
     {"9pm": "Time", "Krakow": "Location"}),
    ("The memo, dated 4/17/2022, instructed branch managers to freeze all "
     "hiring until further notice.",
     {"4/17/2022": "Date"}),
    ("Old Mr. Pemberton kept his savings, all $30k of it, under the "
     "floorboards of his cottage.",
     {"Pemberton": "Person", "$30k": "Money"}),
    ("Unemployment in Andalusia fell below 19% for the first time in a "
     "decade.",
     {"Andalusia": "Location", "19%": "Percentage"}),
    ("The orchestra rehearsed until midnight, and Maestro Bellini was "
     "still not satisfied.",
     {"Bellini": "Person"}),
    ("A spokesman for Lufthansa confirmed the Tuesday flight to Nairobi "
     "had been cancelled.",
     {"Lufthansa": "Organization", "Tuesday": "Date",
      "Nairobi": "Location"}),
    ("Rainfall in October was 40% above the historical average across "
     "Provence.",
     {"October": "Date", "40%": "Percentage", "Provence": "Location"}),
    ("The auction house sold the manuscript for $875k to an anonymous "
     "collector from Zurich.",
     {"$875k": "Money", "Zurich": "Location"}),
    ("Councilwoman Ferreira demanded an audit of the transit authority's "
     "accounts.",
     {"Ferreira": "Person"}),
    ("He boarded the 7:15 train to Brno with nothing but a violin case.",
     {"7:15": "Time", "Brno": "Location"}),
    ("The merger between Halvorsen Group and Pacific Dredging closed on "
     "Friday.",
     {"Halvorsen": "Organization", "Group": "Organization",
      "Pacific": "Organization", "Dredging": "Organization",
      "Friday": "Date"}),
    ("Young Tomasz had never seen the sea before the family moved to "
     "Gdansk in 1995.",
     {"Tomasz": "Person", "Gdansk": "Location", "1995": "Date"}),
    ("Shares of Renault slipped 2.8% in early trading in Paris.",
     {"Renault": "Organization", "2.8%": "Percentage", "Paris": "Location"}),
    ("The harvest festival begins at noon on Saturday in the village of "
     "Ribeauville.",
     {"Saturday": "Date", "Ribeauville": "Location"}),
    ("According to the ledger, the estate owed $5,200 to a moneylender "
     "named Graves.",
     {"$5,200": "Money", "Graves": "Person"}),
    ("Interpol circulated the photograph to border posts from Lisbon to "
     "Bucharest.",
     {"Interpol": "Organization", "Lisbon": "Location",
      "Bucharest": "Location"}),
    ("The vote is scheduled for 10:00 on Wednesday, though few expect it "
     "to pass.",
     {"10:00": "Time", "Wednesday": "Date"}),
    ("Grandmother Odile swore the recipe came from a chef in Lyon.",
     {"Odile": "Person", "Lyon": "Location"}),
    ("Quarterly revenue at Maersk grew 11% to $9.8B, beating every "
     "forecast.",
     {"Maersk": "Organization", "11%": "Percentage", "$9.8B": "Money"}),
    ("The expedition left Kathmandu on 2015-04-12 under clear skies.",
     {"Kathmandu": "Location", "2015-04-12": "Date"}),
    ("Sergeant Whitcombe read the names aloud while the rain fell on the "
     "parade ground.",
     {"Whitcombe": "Person"}),
    ("A fire at the Vostok refinery cut output by 15% overnight.",
     {"Vostok": "Organization", "15%": "Percentage"}),
    ("The curtain rose at 8:30pm sharp, and Madame Rostova missed her cue.",
     {"8:30pm": "Time", "Rostova": "Person"}),
    ("Customs officers in Antwerp seized diamonds worth $6.4M on Sunday.",
     {"Antwerp": "Location", "$6.4M": "Money", "Sunday": "Date"}),
    ("The librarian, Miss Abernathy, catalogued every pamphlet printed "
     "before 1900.",
     {"Abernathy": "Person", "1900": "Date"}),
    ("Wheat futures rose 4.2% in Chicago after the drought worsened.",
     {"4.2%": "Percentage", "Chicago": "Location"}),
    ("Envoys from Brussels arrived in Belgrade late on Thursday evening.",
     {"Brussels": "Location", "Belgrade": "Location", "Thursday": "Date"}),
    ("The foreman told Ruiz that the quarry would shut down in November.",
     {"Ruiz": "Person", "November": "Date"}),
    ("Donations to the Red Cross exceeded $2M within a week of the flood.",
     {"Red": "Organization", "Cross": "Organization", "$2M": "Money"}),
    ("Captain Soriano anchored off Valparaiso just before dawn.",
     {"Soriano": "Person", "Valparaiso": "Location"}),
    ("The ministry lowered its growth estimate for 2024 from 3.1% to 2.4%.",
     {"2024": "Date", "3.1%": "Percentage", "2.4%": "Percentage"}),
    ("Uncle Bram kept the shop on Prinsengracht open until 7pm even on "
     "holidays.",
     {"Bram": "Person", "Prinsengracht": "Location", "7pm": "Time"}),
    ("Auditors from Deloitte found a $730k shortfall in the harbor fund.",
     {"Deloitte": "Organization", "$730k": "Money"}),
    ("Snow closed the pass above Innsbruck for the third time that winter.",
     {"Innsbruck": "Location"}),
    ("The treaty, signed in Vienna in 1955, guaranteed the country's "
     "neutrality.",
     {"Vienna": "Location", "1955": "Date"}),
]
