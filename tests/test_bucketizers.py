"""DecisionTreeNumericBucketizer tests (reference DecisionTreeNumericBucketizerTest)."""

import numpy as np

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.ops.bucketizers import (
    DecisionTreeNumericBucketizer,
    DecisionTreeNumericMapBucketizer,
    find_tree_splits,
)
from transmogrifai_tpu.testkit.specs import assert_estimator_spec
from transmogrifai_tpu.types import Real, RealMap, RealNN
from transmogrifai_tpu.utils.vector_metadata import NULL_INDICATOR


def _label():
    return FeatureBuilder.of("label", RealNN).extract_field().as_response()


class TestFindTreeSplits:
    def test_perfect_split(self):
        v = np.array([1.0, 2.0, 3.0, 10.0, 11.0, 12.0])
        y = np.array([0, 0, 0, 1, 1, 1])
        splits = find_tree_splits(v, y)
        assert len(splits) >= 1
        assert 3.0 <= splits[0] < 10.0  # separates the two groups

    def test_no_signal_no_split(self):
        rng = np.random.default_rng(0)
        v = rng.normal(size=200)
        y = rng.integers(0, 2, 200)
        assert find_tree_splits(v, y, min_info_gain=0.05) == []

    def test_constant_label_or_value(self):
        v = np.array([1.0, 2.0, 3.0])
        assert find_tree_splits(v, np.zeros(3)) == []
        assert find_tree_splits(np.ones(3), np.array([0, 1, 0])) == []

    def test_respects_max_depth(self):
        # 4 clusters, alternating labels -> needs depth 2 for all 3 thresholds
        v = np.concatenate([np.full(20, c) for c in [0.0, 10.0, 20.0, 30.0]])
        y = np.concatenate([np.full(20, c) for c in [0, 1, 0, 1]])
        assert len(find_tree_splits(v, y, max_depth=1)) == 1
        assert len(find_tree_splits(v, y, max_depth=3)) == 3

    def test_nan_values_dropped(self):
        v = np.array([1.0, np.nan, 2.0, 10.0, np.nan, 11.0])
        y = np.array([0, 1, 0, 1, 0, 1])
        splits = find_tree_splits(v, y)
        assert len(splits) == 1


class TestDecisionTreeNumericBucketizer:
    def _fixture(self):
        label = _label()
        x = FeatureBuilder.of("x", Real).extract_field().as_predictor()
        vals = [1.0, 2.0, 3.0, None, 10.0, 11.0, 12.0, None]
        ys = [0.0, 0.0, 0.0, 0.0, 1.0, 1.0, 1.0, 1.0]
        ds = Dataset.from_features({"label": ys, "x": vals},
                                   {"label": RealNN, "x": Real})
        return label, x, ds

    def test_fit_transform_and_spec(self):
        label, x, ds = self._fixture()
        stage = DecisionTreeNumericBucketizer()
        out = label.transform_with(stage, x)
        model = assert_estimator_spec(stage, ds)
        col = model.transform(ds)[out.name]
        # 2 buckets + null indicator
        assert col.data.shape == (8, 3)
        np.testing.assert_allclose(col.data.sum(axis=1), 1.0)  # one-hot rows
        # nulls land in the null column
        np.testing.assert_allclose(col.data[3], [0, 0, 1])
        np.testing.assert_allclose(col.data[7], [0, 0, 1])
        # low values bucket 0, high values bucket 1
        assert col.data[0, 0] == 1.0 and col.data[4, 1] == 1.0
        meta = col.meta
        assert meta.columns[-1].indicator_value == NULL_INDICATOR

    def test_no_split_collapses_to_null_indicator(self):
        label = _label()
        x = FeatureBuilder.of("x", Real).extract_field().as_predictor()
        rng = np.random.default_rng(1)
        ds = Dataset.from_features(
            {"label": rng.integers(0, 2, 100).astype(float).tolist(),
             "x": rng.normal(size=100).tolist()},
            {"label": RealNN, "x": Real})
        stage = DecisionTreeNumericBucketizer(min_info_gain=0.1)
        out = label.transform_with(stage, x)
        model = stage.fit(ds)
        col = model.transform(ds)[out.name]
        assert col.data.shape == (100, 1)  # only the null indicator
        assert not model.should_split

    def test_track_invalid(self):
        label, x, ds = self._fixture()
        stage = DecisionTreeNumericBucketizer(track_invalid=True)
        label.transform_with(stage, x)
        model = stage.fit(ds)
        # +inf is invalid (finite check) -> OutOfBounds column
        ds2 = Dataset.from_features({"label": [0.0], "x": [np.inf]},
                                    {"label": RealNN, "x": Real})
        col = model.transform(ds2)[model.output_name]
        assert col.data.shape == (1, 4)  # 2 buckets + invalid + null
        np.testing.assert_allclose(col.data[0], [0, 0, 1, 0])

    def test_dsl_auto_bucketize(self):
        label, x, ds = self._fixture()
        out = x.auto_bucketize(label)
        stage = out.origin_stage
        assert isinstance(stage, DecisionTreeNumericBucketizer)
        model = stage.fit(ds)
        assert model.transform(ds)[out.name].data.shape[1] == 3


class TestDecisionTreeNumericMapBucketizer:
    def test_per_key_splits(self):
        label = _label()
        m = FeatureBuilder.of("m", RealMap).extract_field().as_predictor()
        n = 40
        ys = [float(i % 2) for i in range(n)]
        maps = [{"signal": 5.0 + 10 * (i % 2), "noise": float((i * 7) % 13)}
                for i in range(n)]
        maps[0] = {"noise": 1.0}  # one row missing 'signal'
        ds = Dataset.from_features({"label": ys, "m": maps},
                                   {"label": RealNN, "m": RealMap})
        stage = DecisionTreeNumericMapBucketizer(min_info_gain=0.05)
        out = label.transform_with(stage, m)
        model = stage.fit(ds)
        col = model.transform(ds)[out.name]
        # signal key: 2 buckets + null; noise key: null only
        assert col.data.shape == (n, 4)
        groupings = [c.grouping for c in col.meta.columns]
        assert "signal" in groupings and "noise" in groupings
        # the missing-signal row hits signal's null indicator
        sig_null = [i for i, c in enumerate(col.meta.columns)
                    if c.grouping == "signal" and c.indicator_value == NULL_INDICATOR][0]
        assert col.data[0, sig_null] == 1.0
