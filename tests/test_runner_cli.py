"""WorkflowRunner run types, OpParams config, App scaffold, CLI generator.

Mirrors reference OpWorkflowRunnerTest (all run types end-to-end incl. save/load) and
cli generator tests.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, transmogrify
from transmogrifai_tpu.evaluators.base import Evaluators
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.params import OpParams
from transmogrifai_tpu.readers.files import DataReaders, StreamingReader
from transmogrifai_tpu.workflow.runner import App, RunType, WorkflowRunner

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _df(n=300, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, n)
    c = rng.choice(["a", "b"], n)
    y = (rng.random(n) < 1 / (1 + np.exp(-(2 * x + (c == "a"))))).astype(float)
    return pd.DataFrame({"label": y, "x": x, "c": c})


def _workflow():
    label = FeatureBuilder.RealNN("label").extract_field().as_response()
    fx = FeatureBuilder.Real("x").extract_field().as_predictor()
    fc = FeatureBuilder.PickList("c").extract_field().as_predictor()
    vec = transmogrify([fx, fc])
    checked = label.sanity_check(vec)
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        models=[(LogisticRegression(), [{"reg_param": 0.01}])])
    pred = label.transform_with(sel, checked)
    return Workflow().set_result_features(label, pred), pred


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("runner")
    df = _df()
    wf, pred = _workflow()
    reader = DataReaders.Simple.dataframe(df)
    runner = WorkflowRunner(workflow=wf, train_reader=reader,
                            scoring_reader=reader,
                            evaluator=Evaluators.binary_classification())
    params = OpParams(model_location=str(tmp / "model"),
                      metrics_location=str(tmp / "train_metrics.json"))
    result = runner.run(RunType.TRAIN, params)
    return runner, params, result, df, tmp


class TestRunner:
    def test_train_saves_model_and_metrics(self, trained):
        runner, params, result, df, tmp = trained
        assert os.path.isdir(params.model_location)
        assert os.path.exists(params.metrics_location)
        assert result.metrics["bestModelName"] == "LogisticRegression"
        with open(params.metrics_location) as fh:
            blob = json.load(fh)
        assert blob["runType"] == "train"

    def test_score(self, trained):
        runner, params, result, df, tmp = trained
        p = OpParams(model_location=params.model_location,
                     write_location=str(tmp / "scores.csv"))
        r = runner.run(RunType.SCORE, p)
        assert r.metrics["auROC"] > 0.7
        assert os.path.exists(p.write_location)
        assert len(pd.read_csv(p.write_location)) == len(df)

    def test_evaluate(self, trained):
        runner, params, result, df, tmp = trained
        r = runner.run(RunType.EVALUATE,
                       OpParams(model_location=params.model_location))
        assert "auPR" in r.metrics

    def test_streaming_score(self, trained):
        runner, params, result, df, tmp = trained
        batches = [df.iloc[:100], df.iloc[100:200], df.iloc[200:]]
        runner.streaming_reader = StreamingReader(
            [DataReaders.Simple.dataframe(b) for b in batches])
        r = runner.run(RunType.STREAMING_SCORE,
                       OpParams(model_location=params.model_location,
                                write_location=str(tmp / "stream.csv")))
        assert r.metrics["batches"] == 3
        assert os.path.exists(str(tmp / "stream_0.csv"))

    def test_missing_model_location_raises(self, trained):
        runner, *_ = trained
        with pytest.raises(ValueError, match="model_location"):
            runner.run(RunType.SCORE, OpParams())

    def test_end_handler_called(self, trained):
        runner, params, *_ = trained
        seen = []
        runner.add_application_end_handler(lambda r: seen.append(r.run_type))
        runner.run(RunType.EVALUATE, OpParams(model_location=params.model_location))
        assert seen == [RunType.EVALUATE]


class TestOpParams:
    def test_json_roundtrip(self, tmp_path):
        p = OpParams(stage_params={"SanityChecker": {"max_correlation": 0.8}},
                     model_location="/m", custom_params={"k": 1})
        path = str(tmp_path / "p.json")
        p.save(path)
        q = OpParams.from_file(path)
        assert q.stage_params == p.stage_params
        assert q.model_location == "/m"

    def test_simple_yaml(self):
        p = OpParams.from_string(
            "stageParams:\n  SanityChecker:\n    max_correlation: 0.8\n"
            "modelLocation: /tmp/m\n")
        assert p.stage_params["SanityChecker"]["max_correlation"] == 0.8
        assert p.model_location == "/tmp/m"

    def test_later_config_overrides_earlier_config(self):
        """Only CODE-set params are protected; config can re-override config."""
        from transmogrifai_tpu.checkers.sanity import SanityChecker

        stage = SanityChecker()
        OpParams(stage_params={"SanityChecker": {"max_correlation": 0.5}}) \
            .apply_to_stages([stage])
        OpParams(stage_params={"SanityChecker": {"max_correlation": 0.9}}) \
            .apply_to_stages([stage])
        assert stage.max_correlation == 0.9

    def test_streaming_dataframe_batches(self, trained):
        runner, params, result, df, tmp = trained
        runner.streaming_reader = StreamingReader([df.iloc[:50], df.iloc[50:100]])
        r = runner.run(RunType.STREAMING_SCORE,
                       OpParams(model_location=params.model_location))
        assert r.metrics["batches"] == 2

    def test_code_wins_over_config(self):
        from transmogrifai_tpu.checkers.sanity import SanityChecker

        code_set = SanityChecker(max_correlation=0.7)
        config_only = SanityChecker()
        p = OpParams(stage_params={"SanityChecker": {"max_correlation": 0.5}})
        p.apply_to_stages([code_set, config_only])
        assert code_set.max_correlation == 0.7   # code wins
        assert config_only.max_correlation == 0.5

    def test_unknown_param_rejected(self):
        from transmogrifai_tpu.checkers.sanity import SanityChecker

        p = OpParams(stage_params={"SanityChecker": {"nope": 1}})
        with pytest.raises(ValueError, match="no param"):
            p.apply_to_stages([SanityChecker()])

    def test_workflow_set_parameters(self):
        wf, pred = _workflow()
        p = OpParams(stage_params={"SanityChecker": {"max_correlation": 0.66}})
        wf.set_parameters(p)
        from transmogrifai_tpu.checkers.sanity import SanityChecker
        from transmogrifai_tpu.workflow.dag import all_stages

        sc = next(s for s in all_stages(wf.result_features)
                  if isinstance(s, SanityChecker))
        assert sc.max_correlation == 0.66


class TestApp:
    def test_app_main(self, trained, tmp_path):
        runner, params, *_ = trained

        class MyApp(App):
            def runner(self, p):
                return runner

        r = MyApp().main(["--run-type", "evaluate",
                          "--model-location", params.model_location])
        assert "auPR" in r.metrics


class TestCliGen:
    def test_generate_and_run_project(self, tmp_path):
        from transmogrifai_tpu.cli import detect_problem_kind, generate_project

        csv = str(tmp_path / "data.csv")
        _df(150, seed=3).to_csv(csv, index=False)
        assert detect_problem_kind(csv, "label").value == "binary"
        out, kind = generate_project(csv, "label", str(tmp_path / "proj"),
                                     name="my-test-app")
        assert kind.value == "binary"
        assert os.path.exists(os.path.join(out, "main.py"))
        assert os.path.exists(os.path.join(out, "README.md"))
        assert os.path.exists(os.path.join(out, "test_project.py"))
        # the generated project must actually train end-to-end
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "main.py", "--run-type", "train",
             "--model-location", str(tmp_path / "m"),
             "--metrics-location", str(tmp_path / "metrics.json")],
            cwd=out, env=env, capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stderr[-2000:]
        assert os.path.exists(str(tmp_path / "metrics.json"))

    def test_regression_detection(self, tmp_path):
        from transmogrifai_tpu.cli import detect_problem_kind

        csv = str(tmp_path / "r.csv")
        pd.DataFrame({"y": np.random.default_rng(0).normal(0, 1, 100),
                      "x": range(100)}).to_csv(csv, index=False)
        assert detect_problem_kind(csv, "y").value == "regression"

    def test_multiclass_detection(self, tmp_path):
        from transmogrifai_tpu.cli import detect_problem_kind

        csv = str(tmp_path / "m.csv")
        pd.DataFrame({"y": [0, 1, 2] * 30, "x": range(90)}).to_csv(csv, index=False)
        assert detect_problem_kind(csv, "y").value == "multiclass"

    def test_string_label_detection(self, tmp_path):
        from transmogrifai_tpu.cli import detect_problem_kind

        csv = str(tmp_path / "s.csv")
        pd.DataFrame({"y": ["cat", "dog", "bird"] * 30,
                      "x": range(90)}).to_csv(csv, index=False)
        assert detect_problem_kind(csv, "y").value == "multiclass"

    @pytest.mark.slow  # full generated-project train; the e2e CLI train
    # path is covered in tier-1 by test_generate_and_run_project
    def test_string_label_project_trains(self, tmp_path):
        """String-labeled response: generator must label-encode, not crash at train."""
        from transmogrifai_tpu.cli import generate_project

        rng = np.random.default_rng(7)
        x = rng.normal(size=120)
        df = pd.DataFrame({
            "x": x,
            "z": rng.normal(size=120),
            "label": np.where(x + 0.3 * rng.normal(size=120) > 0, "yes", "no"),
        })
        csv = str(tmp_path / "s.csv")
        df.to_csv(csv, index=False)
        out, kind = generate_project(csv, "label", str(tmp_path / "proj"))
        assert kind.value == "binary"
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "main.py", "--run-type", "train",
             "--model-location", str(tmp_path / "m"),
             "--metrics-location", str(tmp_path / "metrics.json")],
            cwd=out, env=env, capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stderr[-2000:]

    def test_bad_response_rejected(self, tmp_path):
        from transmogrifai_tpu.cli import generate_project

        csv = str(tmp_path / "d.csv")
        _df(50).to_csv(csv, index=False)
        with pytest.raises(ValueError, match="response"):
            generate_project(csv, "nope", str(tmp_path / "p"))


class TestCliServeFleet:
    """``cli serve --models DIR`` (ISSUE 12 satellite): multi-model replay
    with a tenant column in the JSONL in/out contract."""

    @pytest.fixture(scope="class")
    def fleet_dir(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("fleet")
        df = _df(seed=5)
        wf, pred = _workflow()
        model = (wf.set_reader(DataReaders.Simple.dataframe(df))).train()
        models = tmp / "models"
        for tenant in ("acme", "globex"):
            model.save(str(models / tenant))
        return tmp, models, model, pred

    def test_models_dir_round_trip(self, fleet_dir, tmp_path):
        """Records route by their tenant column; every output row echoes
        the tenant back and per-tenant scores match the single-model
        serving plan bitwise."""
        tmp, models, model, pred = fleet_dir
        recs = [{"x": float(i) / 7 - 1.0, "c": "a" if i % 2 else "b"}
                for i in range(12)]
        lines = [json.dumps({"tenant": ("acme" if i % 2 else "globex"),
                             **r}) for i, r in enumerate(recs)]
        rec_file = tmp_path / "records.jsonl"
        rec_file.write_text("\n".join(lines) + "\n")
        out_file = tmp_path / "scores.jsonl"
        metrics_file = tmp_path / "metrics.json"

        from transmogrifai_tpu.cli.gen import main

        # warm stays ON: the second tenant's ladder must come from the
        # shared executable cache (the dedup figure asserted below)
        rc = main(["serve", "--models", str(models),
                   "--records", str(rec_file),
                   "--output", str(out_file),
                   "--metrics-out", str(metrics_file),
                   "--max-batch", "8", "--max-wait-ms", "1",
                   "--min-bucket", "8"])
        assert rc == 0
        rows = [json.loads(line) for line in
                out_file.read_text().splitlines()]
        assert len(rows) == 12
        # tenant column round-trips in input order
        assert [r["tenant"] for r in rows] == \
            [("acme" if i % 2 else "globex") for i in range(12)]
        loaded = model.__class__.load(str(models / "acme"))
        plan = loaded.serving_plan()
        expected = plan.score(recs)
        for row, exp in zip(rows, expected):
            got = {k: v for k, v in row.items() if k != "tenant"}
            assert got == json.loads(json.dumps(exp))
        metrics = json.loads(metrics_file.read_text())
        assert sorted(metrics["tenants"]) == ["acme", "globex"]
        assert metrics["replay"]["tenants"] == ["acme", "globex"]
        assert metrics["replay"]["record_errors"] == 0
        assert metrics["tenants"]["acme"]["scored_records"] == 6
        assert metrics["tenants"]["globex"]["scored_records"] == 6
        # both subdirectories hold the same saved model: the second tenant
        # registered against the shared fingerprint
        assert metrics["fleet"]["shared_prefix_registrations"] == 1

    def test_models_dir_unknown_tenant_is_error_row(self, fleet_dir,
                                                    tmp_path):
        tmp, models, model, pred = fleet_dir
        lines = [json.dumps({"tenant": "acme", "x": 0.5, "c": "a"}),
                 json.dumps({"x": 0.5, "c": "a"}),            # no tenant
                 json.dumps({"tenant": "nope", "x": 0.5, "c": "a"})]
        rec_file = tmp_path / "records.jsonl"
        rec_file.write_text("\n".join(lines) + "\n")
        out_file = tmp_path / "scores.jsonl"

        from transmogrifai_tpu.cli.gen import main

        rc = main(["serve", "--models", str(models),
                   "--records", str(rec_file),
                   "--output", str(out_file),
                   "--max-batch", "4", "--max-wait-ms", "1", "--no-warm"])
        assert rc != 0  # record errors surface in the exit code
        rows = [json.loads(line) for line in
                out_file.read_text().splitlines()]
        assert len(rows) == 3
        assert "error" not in rows[0] and rows[0]["tenant"] == "acme"
        assert rows[1]["error_type"] == "UnknownTenantError"
        assert rows[2]["error_type"] == "UnknownTenantError"
        assert rows[2]["tenant"] == "nope"

    def test_model_and_models_are_mutually_exclusive(self, fleet_dir,
                                                     tmp_path):
        tmp, models, *_ = fleet_dir
        rec_file = tmp_path / "r.jsonl"
        rec_file.write_text(json.dumps({"tenant": "acme", "x": 1.0,
                                        "c": "a"}) + "\n")

        from transmogrifai_tpu.cli.gen import main

        with pytest.raises(SystemExit, match="mutually exclusive"):
            main(["serve", "--model", str(models / "acme"),
                  "--models", str(models), "--records", str(rec_file)])
        with pytest.raises(SystemExit, match="one of --model or --models"):
            main(["serve", "--records", str(rec_file)])
        with pytest.raises(SystemExit, match="single-model only"):
            main(["serve", "--models", str(models), "--follow",
                  "--records", str(rec_file)])


_HAZARD_SOURCE = '''\
import jax.numpy as jnp


class Sneaky:
    def transform_columns(self, cols, dataset):
        x = jnp.asarray(cols[0].data)
        return float(jnp.sum(x))  # blocking host sync -> TM301
'''

_CLEAN_SOURCE = '''\
import numpy as np


class Fine:
    def transform_columns(self, cols, dataset):
        return np.cumsum(cols[0].data)
'''


class TestCliLint:
    """``python -m transmogrifai_tpu.cli lint`` — prints typed diagnostics
    and exits non-zero on findings (docs/static_analysis.md)."""

    def _lint(self, *args):
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "transmogrifai_tpu.cli", "lint", *args],
            env=env, capture_output=True, text=True, timeout=300)

    def test_hazard_file_exits_nonzero_with_code(self, tmp_path):
        p = tmp_path / "sneaky.py"
        p.write_text(_HAZARD_SOURCE)
        r = self._lint("--path", str(p))
        assert r.returncode == 1, r.stdout + r.stderr
        assert "TM301" in r.stdout
        assert "fix:" in r.stdout

    def test_no_target_exits_nonzero(self):
        r = self._lint()  # neither --path nor --workflow: refuse, don't go green
        assert r.returncode != 0
        assert "nothing to lint" in r.stderr

    def test_missing_path_exits_nonzero(self):
        r = self._lint("--path", "/nonexistent/dir")
        assert r.returncode != 0
        assert "does not exist" in r.stderr

    def test_syntax_error_file_reports_tm305_without_masking(self, tmp_path):
        (tmp_path / "bad.py").write_text("def broken(:\n")
        (tmp_path / "haz.py").write_text(_HAZARD_SOURCE)
        r = self._lint("--path", str(tmp_path))
        assert r.returncode == 1, r.stdout + r.stderr
        assert "TM305" in r.stdout   # the unparseable file is a finding...
        assert "TM301" in r.stdout   # ...and does not mask the real hazard

    def test_clean_file_exits_zero(self, tmp_path):
        p = tmp_path / "fine.py"
        p.write_text(_CLEAN_SOURCE)
        r = self._lint("--path", str(p))
        assert r.returncode == 0, r.stdout + r.stderr
        assert "no issues found" in r.stdout

    def test_json_output(self, tmp_path):
        p = tmp_path / "sneaky.py"
        p.write_text(_HAZARD_SOURCE)
        r = self._lint("--path", str(p), "--json")
        assert r.returncode == 1
        blob = json.loads(r.stdout)
        assert blob[0]["code"] == "TM301"
        assert blob[0]["severity"] == "warning"

    def test_workflow_mode_validates_dag(self, tmp_path):
        wf_src = '''\
from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.data.dataset import Column
from transmogrifai_tpu.stages.base import BinaryTransformer
from transmogrifai_tpu.types import Integral, OPVector, Real


class LintDemoBadConcat(BinaryTransformer):
    input_types = (Real, Integral)
    output_type = OPVector

    def device_transform(self, x, y):
        from jax import lax
        return lax.concatenate([x.reshape(-1, 1), y.reshape(-1, 1)], dimension=1)

    def transform_columns(self, cols, dataset):
        raise NotImplementedError


def build():
    a = FeatureBuilder.Real("a").extract_field().as_predictor()
    n = FeatureBuilder.Integral("n").extract_field().as_predictor()
    return Workflow().set_result_features(a.transform_with(LintDemoBadConcat(), n))
'''
        (tmp_path / "lintdemo.py").write_text(wf_src)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=f"{REPO_ROOT}{os.pathsep}{tmp_path}")
        r = subprocess.run(
            [sys.executable, "-m", "transmogrifai_tpu.cli", "lint",
             "--workflow", "lintdemo:build", "--fail-on", "error"],
            env=env, capture_output=True, text=True, timeout=300)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "TM204" in r.stdout

    def test_format_json_one_diagnostic_per_line(self, tmp_path):
        """Satellite (ISSUE 6): machine-readable JSONL — one diagnostic per
        line with code/severity/stageUid/message — the lint_gate contract."""
        p = tmp_path / "sneaky.py"
        p.write_text(_HAZARD_SOURCE)
        r = self._lint("--path", str(p), "--format", "json")
        assert r.returncode == 1
        lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
        assert lines, r.stdout
        for obj in lines:
            assert {"code", "severity", "stageUid", "message"} <= set(obj)
        assert lines[0]["code"] == "TM301"
        assert lines[0]["severity"] == "warning"

    def test_concurrency_flag_adds_tm306(self, tmp_path):
        p = tmp_path / "caches.py"
        p.write_text("_CACHE = {}\n"
                     "def put(k, v):\n"
                     "    _CACHE[k] = v\n")
        clean = self._lint("--path", str(p), "--all-functions")
        assert clean.returncode == 0, clean.stdout + clean.stderr
        r = self._lint("--path", str(p), "--concurrency")
        assert r.returncode == 1
        assert "TM306" in r.stdout

    def test_threads_json_round_trip(self, tmp_path):
        """Satellite (ISSUE 16): ``--threads --format json`` emits exactly
        one ``{"threadModel": ...}`` summary line plus one TM31x diagnostic
        per line, all parseable — the threads-gate contract."""
        p = tmp_path / "racy.py"
        p.write_text(
            "import threading\n\n"
            "class Counter:\n"
            "    def __init__(self):\n"
            "        self._n = 0\n"
            "        self._t = threading.Thread(target=self._run)\n\n"
            "    def _run(self):\n"
            "        self._n += 1\n\n"
            "    def bump(self):\n"
            "        self._n += 1\n")
        r = self._lint("--path", str(p), "--threads", "--format", "json",
                       "--fail-on", "error")
        assert r.returncode == 1, r.stdout + r.stderr
        lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
        models = [obj for obj in lines if "threadModel" in obj]
        diags = [obj for obj in lines if "code" in obj]
        assert len(models) == 1, r.stdout
        model = models[0]["threadModel"]
        assert {"threads", "sharedClasses", "waiters", "callbacks",
                "lockOrderEdges", "analyzedFiles"} <= set(model)
        assert model["threads"][0]["target"] == "Counter._run"
        assert model["sharedClasses"] == ["Counter"]
        assert model["analyzedFiles"] == 1
        # the summary line comes FIRST (gates stream-parse diagnostics)
        assert "threadModel" in lines[0]
        assert diags, r.stdout
        for obj in diags:
            assert {"code", "severity", "stageUid", "location",
                    "message"} <= set(obj)
            assert obj["code"] == "TM312"
            assert obj["severity"] == "error"

    def test_threads_clean_surface_exits_zero(self, tmp_path):
        p = tmp_path / "fine.py"
        p.write_text(
            "import threading\n\n"
            "class Counter:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n"
            "        self._t = threading.Thread(target=self._run)\n\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n")
        r = self._lint("--path", str(p), "--threads", "--format", "json")
        assert r.returncode == 0, r.stdout + r.stderr
        lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
        assert len(lines) == 1 and "threadModel" in lines[0], r.stdout

    def test_threads_without_path_refuses(self):
        r = self._lint("--threads")
        assert r.returncode != 0
        assert "nothing to lint" in r.stderr


class TestCliLintCost:
    """``cli lint --cost`` (ISSUE 6 tentpole): the PlanCostReport from the
    command line, with the TM601 HBM admission error on a tiny budget."""

    @pytest.fixture(scope="class")
    def saved_model(self, tmp_path_factory):
        import pandas as pd

        from transmogrifai_tpu import (
            BinaryClassificationModelSelector,
            transmogrify,
        )
        from transmogrifai_tpu.models.logistic import LogisticRegression
        from transmogrifai_tpu.readers.files import DataReaders

        rng = np.random.default_rng(13)
        records = [{"label": float(rng.random() < 0.5),
                    "x1": float(rng.normal()),
                    "x2": float(rng.normal())} for _ in range(200)]
        label = FeatureBuilder.RealNN("label").extract_field().as_response()
        f1 = FeatureBuilder.Real("x1").extract_field().as_predictor()
        f2 = FeatureBuilder.Real("x2").extract_field().as_predictor()
        vec = transmogrify([f1, f2])
        checked = label.sanity_check(vec)
        sel = BinaryClassificationModelSelector.with_train_validation_split(
            models=[(LogisticRegression(), [{"reg_param": 0.01}])])
        pred = label.transform_with(sel, checked)
        model = (Workflow().set_result_features(label, pred)
                 .set_reader(DataReaders.Simple.dataframe(
                     pd.DataFrame(records)))).train()
        path = str(tmp_path_factory.mktemp("m") / "model")
        model.save(path)
        return path

    def _lint(self, *args):
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "transmogrifai_tpu.cli", "lint", *args],
            env=env, capture_output=True, text=True, timeout=300)

    def test_cost_emits_plan_cost_report(self, saved_model):
        r = self._lint("--model", saved_model, "--cost",
                       "--format", "json", "--fail-on", "error")
        assert r.returncode == 0, r.stdout + r.stderr
        lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
        reports = [ln["planCostReport"] for ln in lines
                   if "planCostReport" in ln]
        assert len(reports) == 1
        rep = reports[0]
        assert rep["totalFlops"] > 0 and rep["totalBytes"] > 0
        assert rep["buckets"] and all(
            b["peakHbmBytes"] > 0 for b in rep["buckets"])

    def test_cost_text_mode_prints_report(self, saved_model):
        r = self._lint("--model", saved_model, "--cost", "--fail-on", "error")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "PlanCostReport" in r.stdout
        assert "peak HBM" in r.stdout

    def test_tiny_hbm_budget_fires_tm601_rc1(self, saved_model):
        r = self._lint("--model", saved_model, "--hbm-budget", "16",
                       "--format", "json", "--fail-on", "error")
        assert r.returncode == 1, r.stdout + r.stderr
        lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
        codes = [ln.get("code") for ln in lines if "code" in ln]
        assert "TM601" in codes

    def test_cost_without_target_refuses(self, tmp_path):
        p = tmp_path / "fine.py"
        p.write_text("x = 1\n")
        r = self._lint("--cost", "--path", str(p))  # path is no cost target
        assert r.returncode != 0
        assert "--workflow or --model" in r.stderr


#: documented key sets of the three ``--format json`` line types
#: (docs/static_analysis.md "Machine-readable output") — the contract the
#: tools/*_gate.py parsers and any downstream tooling rely on
_JSONL_DIAGNOSTIC_KEYS = {"code", "severity", "stageUid", "location",
                          "message", "fixHint"}
_JSONL_PLAN_COST_KEYS = {"plan", "totalFlops", "totalBytes", "peakHbmBytes",
                         "buckets", "segments", "recompileHazards",
                         "collectives", "orderSensitiveOps", "mesh", "notes"}
_JSONL_IR_DIFF_KEYS = {"compared", "changed", "skipped", "counts",
                       "goldenJaxVersion", "currentJaxVersion",
                       "goldenPlatform", "currentPlatform"}


class TestCliLintJsonRoundTrip:
    """Satellite (ISSUE 7): EVERY ``--format json`` line — diagnostic,
    planCostReport, and the new irDiff — parses as one JSON object and
    carries its documented keys, in one combined invocation."""

    def _lint(self, *args):
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "transmogrifai_tpu.cli", "lint", *args],
            env=env, capture_output=True, text=True, timeout=300)

    def test_all_three_line_types_round_trip(self, tmp_path):
        p = tmp_path / "sneaky.py"
        p.write_text(_HAZARD_SOURCE)  # seeds a TM301 diagnostic line
        r = self._lint("--path", str(p),
                       "--ir", "--ir-family", "models.linear",
                       "--format", "json")
        assert r.returncode == 1, r.stdout + r.stderr  # TM301 >= warning
        lines = r.stdout.strip().splitlines()
        assert lines
        kinds = {"diagnostic": 0, "planCostReport": 0, "irDiff": 0}
        for ln in lines:
            obj = json.loads(ln)  # every line is one JSON object
            assert isinstance(obj, dict)
            if "planCostReport" in obj:
                kinds["planCostReport"] += 1
                assert _JSONL_PLAN_COST_KEYS <= set(obj["planCostReport"])
            elif "irDiff" in obj:
                kinds["irDiff"] += 1
                assert _JSONL_IR_DIFF_KEYS <= set(obj["irDiff"])
            else:
                kinds["diagnostic"] += 1
                assert _JSONL_DIAGNOSTIC_KEYS <= set(obj), obj
        assert kinds["irDiff"] == 1
        assert kinds["diagnostic"] >= 1
        codes = [json.loads(ln).get("code") for ln in lines]
        assert "TM301" in codes

    def test_ir_diff_line_reports_clean_corpus(self):
        r = self._lint("--ir", "--ir-family", "models.linear",
                       "--format", "json")
        assert r.returncode == 0, r.stdout + r.stderr
        lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
        ir = [ln["irDiff"] for ln in lines if "irDiff" in ln]
        assert len(ir) == 1
        assert ir[0]["compared"] == 1 and ir[0]["changed"] == []
        # with a clean corpus no diagnostic lines are emitted at all
        assert not [ln for ln in lines if "code" in ln]

    def test_legacy_json_array_carries_ir_diff_element(self):
        r = self._lint("--ir", "--ir-family", "models.linear", "--json")
        assert r.returncode == 0, r.stdout + r.stderr
        blob = json.loads(r.stdout)
        assert isinstance(blob, list)
        ir = [el["irDiff"] for el in blob if "irDiff" in el]
        assert len(ir) == 1
        assert _JSONL_IR_DIFF_KEYS <= set(ir[0])

    def test_plan_cost_line_keys(self, tmp_path):
        """planCostReport JSONL keys, exercised via a workflow target (the
        unfitted-workflow cost report needs no training)."""
        wf_src = '''\
from transmogrifai_tpu import FeatureBuilder, Workflow, transmogrify


def build():
    a = FeatureBuilder.Real("a").extract_field().as_predictor()
    return Workflow().set_result_features(transmogrify([a]))
'''
        (tmp_path / "jsondemo.py").write_text(wf_src)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=f"{REPO_ROOT}{os.pathsep}{tmp_path}")
        r = subprocess.run(
            [sys.executable, "-m", "transmogrifai_tpu.cli", "lint",
             "--workflow", "jsondemo:build", "--cost", "--format", "json",
             "--fail-on", "error"],
            env=env, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
        reports = [ln["planCostReport"] for ln in lines
                   if "planCostReport" in ln]
        assert len(reports) == 1
        assert _JSONL_PLAN_COST_KEYS <= set(reports[0])


class TestLintGate:
    """tools/lint_gate.py (ISSUE 6 satellite): rc flips ONLY on NEW errors —
    INFO/WARNING never gate; baselined errors pass; --update-baseline."""

    def _gate(self, *args, cwd):
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools", "lint_gate.py"),
             *args],
            env=env, cwd=cwd, capture_output=True, text=True, timeout=300)

    def test_warnings_never_flip_rc(self, tmp_path):
        p = tmp_path / "warn.py"
        p.write_text(_HAZARD_SOURCE)  # TM301 warning
        r = self._gate("--baseline", str(tmp_path / "b.json"),
                       "--", "--path", str(p), cwd=tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "never gates" in r.stdout

    def test_lint_crash_is_not_green(self, tmp_path):
        """A lint that refuses to run (bad --model path, lost args) emits no
        parseable diagnostics — the gate must FAIL, not report OK."""
        r = self._gate("--baseline", str(tmp_path / "b.json"),
                       "--", "--model", str(tmp_path / "nope"), cwd=tmp_path)
        assert r.returncode != 0, r.stdout + r.stderr
        assert "refusing to report OK" in r.stderr

    def test_new_error_fails_then_baseline_passes(self, tmp_path):
        (tmp_path / "bad.py").write_text("def broken(:\n")  # TM305 error
        baseline = str(tmp_path / "b.json")
        r = self._gate("--baseline", baseline,
                       "--", "--path", str(tmp_path), cwd=tmp_path)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "NEW error" in r.stdout
        up = self._gate("--baseline", baseline, "--update-baseline",
                        "--", "--path", str(tmp_path), cwd=tmp_path)
        assert up.returncode == 0, up.stdout + up.stderr
        again = self._gate("--baseline", baseline,
                           "--", "--path", str(tmp_path), cwd=tmp_path)
        assert again.returncode == 0, again.stdout + again.stderr
        assert "known error" in again.stdout
