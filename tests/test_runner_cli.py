"""WorkflowRunner run types, OpParams config, App scaffold, CLI generator.

Mirrors reference OpWorkflowRunnerTest (all run types end-to-end incl. save/load) and
cli generator tests.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow, transmogrify
from transmogrifai_tpu.evaluators.base import Evaluators
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.params import OpParams
from transmogrifai_tpu.readers.files import DataReaders, StreamingReader
from transmogrifai_tpu.workflow.runner import App, RunType, WorkflowRunner

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _df(n=300, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, n)
    c = rng.choice(["a", "b"], n)
    y = (rng.random(n) < 1 / (1 + np.exp(-(2 * x + (c == "a"))))).astype(float)
    return pd.DataFrame({"label": y, "x": x, "c": c})


def _workflow():
    label = FeatureBuilder.RealNN("label").extract_field().as_response()
    fx = FeatureBuilder.Real("x").extract_field().as_predictor()
    fc = FeatureBuilder.PickList("c").extract_field().as_predictor()
    vec = transmogrify([fx, fc])
    checked = label.sanity_check(vec)
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        models=[(LogisticRegression(), [{"reg_param": 0.01}])])
    pred = label.transform_with(sel, checked)
    return Workflow().set_result_features(label, pred), pred


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("runner")
    df = _df()
    wf, pred = _workflow()
    reader = DataReaders.Simple.dataframe(df)
    runner = WorkflowRunner(workflow=wf, train_reader=reader,
                            scoring_reader=reader,
                            evaluator=Evaluators.binary_classification())
    params = OpParams(model_location=str(tmp / "model"),
                      metrics_location=str(tmp / "train_metrics.json"))
    result = runner.run(RunType.TRAIN, params)
    return runner, params, result, df, tmp


class TestRunner:
    def test_train_saves_model_and_metrics(self, trained):
        runner, params, result, df, tmp = trained
        assert os.path.isdir(params.model_location)
        assert os.path.exists(params.metrics_location)
        assert result.metrics["bestModelName"] == "LogisticRegression"
        with open(params.metrics_location) as fh:
            blob = json.load(fh)
        assert blob["runType"] == "train"

    def test_score(self, trained):
        runner, params, result, df, tmp = trained
        p = OpParams(model_location=params.model_location,
                     write_location=str(tmp / "scores.csv"))
        r = runner.run(RunType.SCORE, p)
        assert r.metrics["auROC"] > 0.7
        assert os.path.exists(p.write_location)
        assert len(pd.read_csv(p.write_location)) == len(df)

    def test_evaluate(self, trained):
        runner, params, result, df, tmp = trained
        r = runner.run(RunType.EVALUATE,
                       OpParams(model_location=params.model_location))
        assert "auPR" in r.metrics

    def test_streaming_score(self, trained):
        runner, params, result, df, tmp = trained
        batches = [df.iloc[:100], df.iloc[100:200], df.iloc[200:]]
        runner.streaming_reader = StreamingReader(
            [DataReaders.Simple.dataframe(b) for b in batches])
        r = runner.run(RunType.STREAMING_SCORE,
                       OpParams(model_location=params.model_location,
                                write_location=str(tmp / "stream.csv")))
        assert r.metrics["batches"] == 3
        assert os.path.exists(str(tmp / "stream_0.csv"))

    def test_missing_model_location_raises(self, trained):
        runner, *_ = trained
        with pytest.raises(ValueError, match="model_location"):
            runner.run(RunType.SCORE, OpParams())

    def test_end_handler_called(self, trained):
        runner, params, *_ = trained
        seen = []
        runner.add_application_end_handler(lambda r: seen.append(r.run_type))
        runner.run(RunType.EVALUATE, OpParams(model_location=params.model_location))
        assert seen == [RunType.EVALUATE]


class TestOpParams:
    def test_json_roundtrip(self, tmp_path):
        p = OpParams(stage_params={"SanityChecker": {"max_correlation": 0.8}},
                     model_location="/m", custom_params={"k": 1})
        path = str(tmp_path / "p.json")
        p.save(path)
        q = OpParams.from_file(path)
        assert q.stage_params == p.stage_params
        assert q.model_location == "/m"

    def test_simple_yaml(self):
        p = OpParams.from_string(
            "stageParams:\n  SanityChecker:\n    max_correlation: 0.8\n"
            "modelLocation: /tmp/m\n")
        assert p.stage_params["SanityChecker"]["max_correlation"] == 0.8
        assert p.model_location == "/tmp/m"

    def test_later_config_overrides_earlier_config(self):
        """Only CODE-set params are protected; config can re-override config."""
        from transmogrifai_tpu.checkers.sanity import SanityChecker

        stage = SanityChecker()
        OpParams(stage_params={"SanityChecker": {"max_correlation": 0.5}}) \
            .apply_to_stages([stage])
        OpParams(stage_params={"SanityChecker": {"max_correlation": 0.9}}) \
            .apply_to_stages([stage])
        assert stage.max_correlation == 0.9

    def test_streaming_dataframe_batches(self, trained):
        runner, params, result, df, tmp = trained
        runner.streaming_reader = StreamingReader([df.iloc[:50], df.iloc[50:100]])
        r = runner.run(RunType.STREAMING_SCORE,
                       OpParams(model_location=params.model_location))
        assert r.metrics["batches"] == 2

    def test_code_wins_over_config(self):
        from transmogrifai_tpu.checkers.sanity import SanityChecker

        code_set = SanityChecker(max_correlation=0.7)
        config_only = SanityChecker()
        p = OpParams(stage_params={"SanityChecker": {"max_correlation": 0.5}})
        p.apply_to_stages([code_set, config_only])
        assert code_set.max_correlation == 0.7   # code wins
        assert config_only.max_correlation == 0.5

    def test_unknown_param_rejected(self):
        from transmogrifai_tpu.checkers.sanity import SanityChecker

        p = OpParams(stage_params={"SanityChecker": {"nope": 1}})
        with pytest.raises(ValueError, match="no param"):
            p.apply_to_stages([SanityChecker()])

    def test_workflow_set_parameters(self):
        wf, pred = _workflow()
        p = OpParams(stage_params={"SanityChecker": {"max_correlation": 0.66}})
        wf.set_parameters(p)
        from transmogrifai_tpu.checkers.sanity import SanityChecker
        from transmogrifai_tpu.workflow.dag import all_stages

        sc = next(s for s in all_stages(wf.result_features)
                  if isinstance(s, SanityChecker))
        assert sc.max_correlation == 0.66


class TestApp:
    def test_app_main(self, trained, tmp_path):
        runner, params, *_ = trained

        class MyApp(App):
            def runner(self, p):
                return runner

        r = MyApp().main(["--run-type", "evaluate",
                          "--model-location", params.model_location])
        assert "auPR" in r.metrics


class TestCliGen:
    def test_generate_and_run_project(self, tmp_path):
        from transmogrifai_tpu.cli import detect_problem_kind, generate_project

        csv = str(tmp_path / "data.csv")
        _df(150, seed=3).to_csv(csv, index=False)
        assert detect_problem_kind(csv, "label").value == "binary"
        out, kind = generate_project(csv, "label", str(tmp_path / "proj"),
                                     name="my-test-app")
        assert kind.value == "binary"
        assert os.path.exists(os.path.join(out, "main.py"))
        assert os.path.exists(os.path.join(out, "README.md"))
        assert os.path.exists(os.path.join(out, "test_project.py"))
        # the generated project must actually train end-to-end
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "main.py", "--run-type", "train",
             "--model-location", str(tmp_path / "m"),
             "--metrics-location", str(tmp_path / "metrics.json")],
            cwd=out, env=env, capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stderr[-2000:]
        assert os.path.exists(str(tmp_path / "metrics.json"))

    def test_regression_detection(self, tmp_path):
        from transmogrifai_tpu.cli import detect_problem_kind

        csv = str(tmp_path / "r.csv")
        pd.DataFrame({"y": np.random.default_rng(0).normal(0, 1, 100),
                      "x": range(100)}).to_csv(csv, index=False)
        assert detect_problem_kind(csv, "y").value == "regression"

    def test_multiclass_detection(self, tmp_path):
        from transmogrifai_tpu.cli import detect_problem_kind

        csv = str(tmp_path / "m.csv")
        pd.DataFrame({"y": [0, 1, 2] * 30, "x": range(90)}).to_csv(csv, index=False)
        assert detect_problem_kind(csv, "y").value == "multiclass"

    def test_string_label_detection(self, tmp_path):
        from transmogrifai_tpu.cli import detect_problem_kind

        csv = str(tmp_path / "s.csv")
        pd.DataFrame({"y": ["cat", "dog", "bird"] * 30,
                      "x": range(90)}).to_csv(csv, index=False)
        assert detect_problem_kind(csv, "y").value == "multiclass"

    def test_string_label_project_trains(self, tmp_path):
        """String-labeled response: generator must label-encode, not crash at train."""
        from transmogrifai_tpu.cli import generate_project

        rng = np.random.default_rng(7)
        x = rng.normal(size=120)
        df = pd.DataFrame({
            "x": x,
            "z": rng.normal(size=120),
            "label": np.where(x + 0.3 * rng.normal(size=120) > 0, "yes", "no"),
        })
        csv = str(tmp_path / "s.csv")
        df.to_csv(csv, index=False)
        out, kind = generate_project(csv, "label", str(tmp_path / "proj"))
        assert kind.value == "binary"
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "main.py", "--run-type", "train",
             "--model-location", str(tmp_path / "m"),
             "--metrics-location", str(tmp_path / "metrics.json")],
            cwd=out, env=env, capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stderr[-2000:]

    def test_bad_response_rejected(self, tmp_path):
        from transmogrifai_tpu.cli import generate_project

        csv = str(tmp_path / "d.csv")
        _df(50).to_csv(csv, index=False)
        with pytest.raises(ValueError, match="response"):
            generate_project(csv, "nope", str(tmp_path / "p"))
