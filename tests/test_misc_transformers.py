"""Misc transformer tests: Exists/Filter/Replace/Substring/ToOccur/DropIndicesBy,
Scaler/Descaler, TimePeriod transformers, DateListVectorizer (SURVEY §2.7)."""

import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.ops.dates import (
    ALL_TIME_PERIODS,
    DateListVectorizer,
    TimePeriodListTransformer,
    TimePeriodMapTransformer,
    TimePeriodTransformer,
    extract_time_period,
)
from transmogrifai_tpu.ops.misc import (
    DescalerTransformer,
    DropIndicesByTransformer,
    ExistsTransformer,
    FilterTransformer,
    ReplaceTransformer,
    ScalerTransformer,
    SubstringTransformer,
    ToOccurTransformer,
)
from transmogrifai_tpu.testkit.specs import assert_transformer_spec
from transmogrifai_tpu.types import (
    Date,
    DateList,
    DateMap,
    Real,
    Text,
)
from transmogrifai_tpu.utils.vector_metadata import NULL_INDICATOR

# 2018-06-13 11:00:00 UTC, a Wednesday
WED_MS = 1528887600000
_DAY = 24 * 3600 * 1000


def _feat(name, ftype):
    return FeatureBuilder.of(name, ftype).extract_field().as_predictor()


def _is_none(v):
    return v is None


def _over_two(v):
    return v is not None and v > 2.0


class TestValueTransformers:
    def test_exists(self):
        f = _feat("x", Real)
        t = ExistsTransformer(predicate=_over_two, input_type=Real)
        f.transform_with(t)
        ds = Dataset.from_features({"x": [1.0, 3.0, None]}, {"x": Real})
        out = assert_transformer_spec(t, ds, expected=[False, True, False],
                                      check_serde=False)

    def test_filter_with_default(self):
        f = _feat("x", Real)
        t = FilterTransformer(predicate=_over_two, default=-1.0, input_type=Real)
        f.transform_with(t)
        ds = Dataset.from_features({"x": [1.0, 3.0, None]}, {"x": Real})
        assert_transformer_spec(t, ds, expected=[-1.0, 3.0, -1.0], check_serde=False)

    def test_replace(self):
        f = _feat("s", Text)
        t = ReplaceTransformer(input_type=Text, old_value="n/a", new_value=None)
        f.transform_with(t)
        ds = Dataset.from_features({"s": ["a", "n/a", "b"]}, {"s": Text})
        assert_transformer_spec(t, ds, expected=["a", None, "b"])

    def test_substring(self):
        sub, full = _feat("sub", Text), _feat("full", Text)
        t = SubstringTransformer()
        sub.transform_with(t, full)
        ds = Dataset.from_features(
            {"sub": ["Cat", "dog", None], "full": ["concatenate", "bird", "x"]},
            {"sub": Text, "full": Text})
        assert_transformer_spec(t, ds, expected=[True, False, None])

    def test_to_occur(self):
        f = _feat("x", Real)
        t = ToOccurTransformer(input_type=Real)
        f.transform_with(t)
        ds = Dataset.from_features({"x": [1.5, 0.0, None]}, {"x": Real})
        assert_transformer_spec(t, ds, expected=[1.0, 0.0, 0.0], check_serde=False)


class TestDropIndicesBy:
    def test_drops_null_indicators(self):
        a, b = _feat("a", Real), _feat("b", Real)
        from transmogrifai_tpu.ops.numeric import NumericVectorizer

        stage = NumericVectorizer()
        vec = a.transform_with(stage, b)
        ds = Dataset.from_features({"a": [1.0, None], "b": [2.0, 3.0]},
                                   {"a": Real, "b": Real})
        model = stage.fit(ds)
        ds2 = model.transform(ds)
        drop = DropIndicesByTransformer(
            match_fn=lambda cm: cm.is_null_indicator)
        vec2 = vec.transform_with(drop)
        out = drop.transform(ds2)[vec2.name]
        assert out.data.shape[1] == 2  # null columns gone
        assert all(not c.is_null_indicator for c in out.meta.columns)
        # index_in_vector re-assigned compactly
        assert [c.index for c in out.meta.columns] == [0, 1]


class TestScalerDescaler:
    def test_linear_roundtrip(self):
        f = _feat("x", Real)
        scaler = ScalerTransformer(scaling_type="linear", slope=2.0, intercept=3.0)
        scaled = f.transform_with(scaler)
        pred = _feat("pred", Real)
        descaler = DescalerTransformer()
        out = pred.transform_with(descaler, scaled)
        ds = Dataset.from_features({"x": [1.0, 2.0], "pred": [5.0, 7.0]},
                                   {"x": Real, "pred": Real})
        ds = scaler.transform(ds)
        assert ds[scaled.name].to_values() == [5.0, 7.0]
        got = descaler.transform(ds)[out.name]
        assert got.to_values() == [1.0, 2.0]

    def test_log_scaler(self):
        f = _feat("x", Real)
        scaler = ScalerTransformer(scaling_type="logarithmic")
        scaled = f.transform_with(scaler)
        ds = Dataset.from_features({"x": [float(np.e)]}, {"x": Real})
        assert scaler.transform(ds)[scaled.name].to_values() == [1.0]

    def test_descaler_requires_scaler_origin(self):
        pred, other = _feat("pred", Real), _feat("other", Real)
        descaler = DescalerTransformer()
        pred.transform_with(descaler, other)
        ds = Dataset.from_features({"pred": [1.0], "other": [2.0]},
                                   {"pred": Real, "other": Real})
        with pytest.raises(ValueError, match="ScalerTransformer"):
            descaler.transform(ds)


class TestTimePeriods:
    def test_known_date_ordinals(self):
        ms = np.array([WED_MS])
        assert extract_time_period(ms, "DayOfWeek")[0] == 3  # Wednesday
        assert extract_time_period(ms, "DayOfMonth")[0] == 13
        assert extract_time_period(ms, "MonthOfYear")[0] == 6
        assert extract_time_period(ms, "HourOfDay")[0] == 11
        assert extract_time_period(ms, "DayOfYear")[0] == 164
        # June 2018: the 1st was a Friday (Mon-start, minimal 1 day) -> 13th in week 3
        assert extract_time_period(ms, "WeekOfMonth")[0] == 3

    def test_all_periods_in_bounds(self):
        rng = np.random.default_rng(0)
        ms = rng.integers(0, 2_000_000_000_000, 500)
        bounds = {"DayOfMonth": (1, 31), "DayOfWeek": (1, 7), "DayOfYear": (1, 366),
                  "HourOfDay": (0, 23), "MonthOfYear": (1, 12),
                  "WeekOfMonth": (1, 6), "WeekOfYear": (1, 54)}
        for p in ALL_TIME_PERIODS:
            vals = extract_time_period(ms, p)
            lo, hi = bounds[p]
            assert vals.min() >= lo and vals.max() <= hi, p

    def test_time_period_transformer(self):
        f = _feat("d", Date)
        t = TimePeriodTransformer(period="DayOfWeek")
        f.transform_with(t)
        ds = Dataset.from_features({"d": [WED_MS, None]}, {"d": Date})
        assert_transformer_spec(t, ds, expected=[3, None])

    def test_time_period_map(self):
        f = _feat("m", DateMap)
        t = TimePeriodMapTransformer(period="MonthOfYear")
        f.transform_with(t)
        ds = Dataset.from_features({"m": [{"a": WED_MS}, None]}, {"m": DateMap})
        out = t.transform(ds)[t.output_name]
        assert out.to_values() == [{"a": 6}, {}]  # empty map stays empty

    def test_time_period_list(self):
        f = _feat("l", DateList)
        t = TimePeriodListTransformer(period="HourOfDay", max_elements=4)
        f.transform_with(t)
        ds = Dataset.from_features({"l": [[WED_MS, WED_MS + 3600_000], None]},
                                   {"l": DateList})
        out = t.transform(ds)[t.output_name]
        # pad slots are -1 so a padded slot can't pose as a real midnight event
        np.testing.assert_allclose(out.data[0], [11, 12, -1, -1])
        np.testing.assert_allclose(out.data[1], -1)

    def test_time_period_list_warns_on_truncation(self):
        f = _feat("l", DateList)
        t = TimePeriodListTransformer(period="HourOfDay", max_elements=2)
        f.transform_with(t)
        ds = Dataset.from_features({"l": [[WED_MS, WED_MS, WED_MS]]},
                                   {"l": DateList})
        with pytest.warns(UserWarning, match="excess events"):
            t.transform(ds)

    def test_integral_output_roundtrips(self):
        """TimePeriodTransformer output must re-materialize as Integral (int, not float)."""
        from transmogrifai_tpu.data.dataset import Column
        from transmogrifai_tpu.types import Integral

        f = _feat("d", Date)
        t = TimePeriodTransformer(period="DayOfWeek")
        f.transform_with(t)
        ds = Dataset.from_features({"d": [WED_MS, None]}, {"d": Date})
        col = t.transform(ds)[t.output_name]
        again = Column.from_values(Integral, col.to_values())
        assert again.to_values() == [3, None]


class TestDateListVectorizer:
    def _ds(self):
        return Dataset.from_features(
            {"l": [[WED_MS - 5 * _DAY, WED_MS - 2 * _DAY], None]},
            {"l": DateList})

    def test_since_first_and_last(self):
        f = _feat("l", DateList)
        t = DateListVectorizer(pivot="SinceFirst", reference_date_ms=WED_MS)
        f.transform_with(t)
        out = t.transform(self._ds())[t.output_name]
        np.testing.assert_allclose(out.data[0], [5.0, 0.0])  # days + null col
        np.testing.assert_allclose(out.data[1], [0.0, 1.0])  # fill + null
        t2 = DateListVectorizer(pivot="SinceLast", reference_date_ms=WED_MS)
        _feat("l", DateList).transform_with(t2)
        out2 = t2.transform(self._ds())[t2.output_name]
        np.testing.assert_allclose(out2.data[0], [2.0, 0.0])

    def test_mode_day_one_hot(self):
        f = _feat("l", DateList)
        # Friday + Friday + Monday -> mode Friday (dow 5)
        ds = Dataset.from_features(
            {"l": [[WED_MS + 2 * _DAY, WED_MS + 9 * _DAY, WED_MS + 5 * _DAY]]},
            {"l": DateList})
        t = DateListVectorizer(pivot="ModeDay")
        f.transform_with(t)
        out = t.transform(ds)[t.output_name]
        assert out.data.shape == (1, 8)  # 7 days + null
        assert out.data[0, 4] == 1.0  # Friday == index 4 (1-based dow 5)
        assert out.meta.columns[-1].indicator_value == NULL_INDICATOR


class TestRandomParamBuilder:
    def test_distributions(self):
        from transmogrifai_tpu.models.random_param import RandomParamBuilder

        grids = (RandomParamBuilder(seed=7)
                 .exponential("reg", 1e-4, 1e-1)
                 .uniform("depth", 2, 8, integer=True)
                 .subset("net", [0.0, 0.5, 1.0])
                 .build(25))
        assert len(grids) == 25
        for g in grids:
            assert 1e-4 <= g["reg"] <= 1e-1
            assert isinstance(g["depth"], int) and 2 <= g["depth"] <= 8
            assert g["net"] in (0.0, 0.5, 1.0)
        # log-uniform: median should sit near the geometric mean, far below midpoint
        regs = sorted(g["reg"] for g in grids)
        assert regs[len(regs) // 2] < 0.02

    def test_validation(self):
        from transmogrifai_tpu.models.random_param import RandomParamBuilder

        with pytest.raises(ValueError, match="less than max"):
            RandomParamBuilder().uniform("a", 5, 2)
        with pytest.raises(ValueError, match="0 < min"):
            RandomParamBuilder().exponential("a", 0.0, 1.0)
        with pytest.raises(ValueError, match="no param"):
            RandomParamBuilder().build(3)


class TestDateListReferenceDateSnapshot:
    def test_default_reference_date_fixed_at_construction(self):
        """None snapshots now() ONCE at construction (reference
        TransmogrifierDefaults.ReferenceDate semantics) so transforms are
        deterministic and serde carries the date into serving."""
        import time

        t = DateListVectorizer(pivot="SinceLast")
        assert t.reference_date_ms is not None
        ref = t.reference_date_ms
        assert abs(ref - time.time() * 1000) < 60_000
        f = _feat("d", DateList)
        f.transform_with(t)
        ds = Dataset.from_features({"d": [[WED_MS]]}, {"d": DateList})
        v1 = t.transform(ds)[t.output_name].data.copy()
        time.sleep(0.05)
        v2 = t.transform(ds)[t.output_name].data
        np.testing.assert_array_equal(v1, v2)
        assert t.copy().reference_date_ms == ref
