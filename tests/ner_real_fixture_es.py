"""Hand-labeled Spanish real-prose NER fixture (VERDICT r4 #3).

110 sentences in news / fiction / correspondence / review register —
subordinate clauses, appositives, quotes, entities at varied positions —
NOT generated from the training templates (tools/train_ner_tagger_multilang.py).
Labels are token -> NameEntityType for every entity token (everything else
is O), using ``ner_tokenize``'s tokenization.

Many names are real-world or invented entities absent from both the
es gazetteers (ops/ner_lang.py) and the training fill lists; common ones
(Madrid, viernes) naturally overlap, as real Spanish text does.
"""

# (sentence, {token: entity_type})
REAL_TEXT_ES = [
    ("Cuando los delegados llegaron por fin a Ginebra, las conversaciones "
     "ya se habían roto, y el secretario Arteaga se negó a declarar.",
     {"Ginebra": "Location", "Arteaga": "Person"}),
    ("La agencia informó el jueves de que Ferrovial recortaría casi el 8% "
     "de su plantilla antes de diciembre.",
     {"jueves": "Date", "Ferrovial": "Organization", "8%": "Percentage",
      "diciembre": "Date"}),
    ("El viejo farero, un hombre llamado Aurelio Zubiaurre, no había "
     "salido de la isla desde 1987.",
     {"Aurelio": "Person", "Zubiaurre": "Person", "1987": "Date"}),
    ("Los analistas de Bankinter esperan que el euro se debilite frente "
     "al dólar antes de la primavera.",
     {"Bankinter": "Organization"}),
    ("A las 6:45 el ferry salió de Algeciras con correo, aceitunas y un "
     "contable muy nervioso.",
     {"6:45": "Time", "Algeciras": "Location"}),
    ("Su hija Beatriz estudió química en Salamanca antes de que empezara "
     "la guerra.",
     {"Beatriz": "Person", "Salamanca": "Location"}),
    ("El acuerdo, aprobado el 2019-03-22, obligaba a Cementos Molins a "
     "pagar €14M en daños.",
     {"2019-03-22": "Date", "Cementos": "Organization",
      "Molins": "Organization", "€14M": "Money"}),
    ("Nadie en el pueblo de Frigiliana recordaba un enero más frío que "
     "aquel.",
     {"Frigiliana": "Location", "enero": "Date"}),
    ("El profesor Oyarzábal sostuvo que las cifras publicadas por el "
     "Banco Mundial subestimaban la pobreza rural en al menos un 3.5%.",
     {"Oyarzábal": "Person", "Banco": "Organization",
      "Mundial": "Organization", "3.5%": "Percentage"}),
    ("Eran casi las 11:30 cuando la inspectora Urrutia llamó a la puerta "
     "del almacén de Vigo.",
     {"11:30": "Time", "Urrutia": "Person", "Vigo": "Location"}),
    ("La facturación de Acerinox subió un 6% el trimestre pasado, dijo la "
     "empresa el lunes.",
     {"Acerinox": "Organization", "6%": "Percentage", "lunes": "Date"}),
    ("En el verano de 2003, dos hermanos de Cádiz abrieron una panadería "
     "en la calle Fuencarral.",
     {"2003": "Date", "Cádiz": "Location", "Fuencarral": "Location"}),
    ("La comisión escuchó el testimonio de la Dra. Lizarraga, que había "
     "dirigido los ensayos en Pamplona.",
     {"Lizarraga": "Person", "Pamplona": "Location"}),
    ("El flete subió a €2,400 por contenedor después de que el canal "
     "cerrara en marzo.",
     {"€2,400": "Money", "marzo": "Date"}),
    ("Mi abuela salió de Oviedo en 1952 con dos maletas y una dirección "
     "en Buenos Aires.",
     {"Oviedo": "Location", "1952": "Date", "Buenos": "Location",
      "Aires": "Location"}),
    ("Repsol y Galp anunciaron el viernes una inversión conjunta de "
     "€350M en energía solar.",
     {"Repsol": "Organization", "Galp": "Organization",
      "viernes": "Date", "€350M": "Money"}),
    ("El tren de las 7:15 a Zaragoza salió con veinte minutos de retraso.",
     {"7:15": "Time", "Zaragoza": "Location"}),
    ("Doña Remedios vendió la finca a un abogado de Badajoz por mucho "
     "menos de lo que valía.",
     {"Remedios": "Person", "Badajoz": "Location"}),
    ("Según el informe de Mapfre, las primas crecieron un 4.2% en "
     "octubre.",
     {"Mapfre": "Organization", "4.2%": "Percentage", "octubre": "Date"}),
    ("El alcalde de Cuenca inauguró el puente un sábado lluvioso.",
     {"Cuenca": "Location", "sábado": "Date"}),
    ("Teodoro Valcárcel, violinista y contrabandista ocasional, murió en "
     "Marsella sin un céntimo.",
     {"Teodoro": "Person", "Valcárcel": "Person", "Marsella": "Location"}),
    ("La tormenta dejó sin luz a medio Montevideo durante la madrugada "
     "del martes.",
     {"Montevideo": "Location", "martes": "Date"}),
    ("Iberdrola colocó bonos verdes por €750M con una demanda que "
     "triplicó la oferta.",
     {"Iberdrola": "Organization", "€750M": "Money"}),
    ("El manuscrito llegó a manos de la editorial Anagrama envuelto en "
     "papel de estraza.",
     {"Anagrama": "Organization"}),
    ("Quedamos a las 19:30 en la estación de Atocha, debajo del reloj.",
     {"19:30": "Time", "Atocha": "Location"}),
    ("El desempleo juvenil bajó al 27% por primera vez desde 2008.",
     {"27%": "Percentage", "2008": "Date"}),
    ("Carmela Espósito cruzó la frontera en Irún con los papeles de su "
     "hermana.",
     {"Carmela": "Person", "Espósito": "Person", "Irún": "Location"}),
    ("El pedido costó €89 y llegó roto; nadie contesta desde el "
     "miércoles.",
     {"€89": "Money", "miércoles": "Date"}),
    ("Ferroglobe presentó resultados el 2021-11-04 y las acciones "
     "subieron un 12%.",
     {"Ferroglobe": "Organization", "2021-11-04": "Date",
      "12%": "Percentage"}),
    ("El comisario Squadritto no creía en las casualidades, y menos en "
     "Nápoles.",
     {"Squadritto": "Person", "Nápoles": "Location"}),
    ("Mi vuelo a Lanzarote sale a las 6:10 y todavía no he hecho la "
     "maleta.",
     {"Lanzarote": "Location", "6:10": "Time"}),
    ("La cosecha de 2019 fue la peor en décadas para los viñedos de "
     "Mendoza.",
     {"2019": "Date", "Mendoza": "Location"}),
    ("El ministro anunció en Bruselas que España aportaría €120M al "
     "fondo.",
     {"Bruselas": "Location", "España": "Location", "€120M": "Money"}),
    ("Aldeasa ganó el concurso de las tiendas del aeropuerto de Barajas.",
     {"Aldeasa": "Organization", "Barajas": "Location"}),
    ("Don Cosme llegaba todos los domingos a las 9:00 con el periódico "
     "bajo el brazo.",
     {"Cosme": "Person", "domingos": "Date", "9:00": "Time"}),
    ("La niebla cubrió Temuco hasta bien entrada la mañana.",
     {"Temuco": "Location"}),
    ("El jurado otorgó el premio a Valeria Luiselli por unanimidad.",
     {"Valeria": "Person", "Luiselli": "Person"}),
    ("Las exportaciones a Portugal cayeron un 9% en el primer semestre.",
     {"Portugal": "Location", "9%": "Percentage"}),
    ("Tía Engracia guardaba €3,000 en una lata de galletas encima del "
     "armario.",
     {"Engracia": "Person", "€3,000": "Money"}),
    ("El autobús de Cáceres a Mérida tarda poco menos de una hora.",
     {"Cáceres": "Location", "Mérida": "Location"}),
    ("Telepizza abrirá cuarenta locales en Chile antes de noviembre.",
     {"Telepizza": "Organization", "Chile": "Location",
      "noviembre": "Date"}),
    ("El catedrático Solozábal presentó su renuncia el 14/06/2022 sin "
     "dar explicaciones.",
     {"Solozábal": "Person", "14/06/2022": "Date"}),
    ("Nos perdimos por los callejones de Albarracín buscando la casa del "
     "herrero.",
     {"Albarracín": "Location"}),
    ("La auditoría de Deloitte encontró un desfase del 2.8% en las "
     "cuentas.",
     {"Deloitte": "Organization", "2.8%": "Percentage"}),
    ("Griselda Pantoja cantó en el Teatro Colón una sola vez, en 1974.",
     {"Griselda": "Person", "Pantoja": "Person", "Teatro": "Location",
      "Colón": "Location", "1974": "Date"}),
    ("El kilo de tomate llegó a €4 en los mercados de Almería.",
     {"€4": "Money", "Almería": "Location"}),
    ("El sábado cerraron el puerto de Valparaíso por el temporal.",
     {"sábado": "Date", "Valparaíso": "Location"}),
    ("Natixis rebajó su previsión de crecimiento para México al 1.9%.",
     {"Natixis": "Organization", "México": "Location",
      "1.9%": "Percentage"}),
    ("El capataz Ormeño contó los sacos dos veces antes de firmar.",
     {"Ormeño": "Person"}),
    ("Nieva en Soria desde el jueves y no hay quitanieves.",
     {"Soria": "Location", "jueves": "Date"}),
    ("La beca cubre €1,200 al mes durante dos años en Heidelberg.",
     {"€1,200": "Money", "Heidelberg": "Location"}),
    ("El notario leyó el testamento ante los hermanos Irigoyen a las "
     "16:00 en punto.",
     {"Irigoyen": "Person", "16:00": "Time"}),
    ("Prosegur trasladó su sede operativa a Alcobendas el año pasado.",
     {"Prosegur": "Organization", "Alcobendas": "Location"}),
    ("El documental sobre Chillida se estrena el 03/10/2024 en San "
     "Sebastián.",
     {"Chillida": "Person", "03/10/2024": "Date", "San": "Location",
      "Sebastián": "Location"}),
    ("Perdí el móvil en un taxi de Guayaquil y nadie lo devolvió.",
     {"Guayaquil": "Location"}),
    ("La ocupación hotelera en Benidorm rozó el 92% en agosto.",
     {"Benidorm": "Location", "92%": "Percentage", "agosto": "Date"}),
    ("El sargento Quiñones pidió refuerzos a las 2:20 de la madrugada.",
     {"Quiñones": "Person", "2:20": "Time"}),
    ("Damm patrocina las fiestas del barrio desde 1998.",
     {"Damm": "Organization", "1998": "Date"}),
    ("El ascensor lleva roto desde el martes y el administrador no "
     "responde.",
     {"martes": "Date"}),
    ("Clarisa Obregón dejó una nota y un billete de €50 sobre la mesa.",
     {"Clarisa": "Person", "Obregón": "Person", "€50": "Money"}),
    ("La ruta por el valle del Jerte es preciosa a finales de marzo.",
     {"Jerte": "Location", "marzo": "Date"}),
    ("Abengoa renegoció su deuda con un descuento del 35%.",
     {"Abengoa": "Organization", "35%": "Percentage"}),
    ("El catalejo del capitán Berenguer apareció en un anticuario de "
     "Brujas.",
     {"Berenguer": "Person", "Brujas": "Location"}),
    ("Hay mercadillo en la plaza los viernes desde las 8:00.",
     {"viernes": "Date", "8:00": "Time"}),
    ("Glovo repartió más de un millón de pedidos en Lima el año pasado.",
     {"Glovo": "Organization", "Lima": "Location"}),
    ("La pensión de la señora Arrizabalaga no llega a €900.",
     {"Arrizabalaga": "Person", "€900": "Money"}),
    ("El incendio arrasó doscientas hectáreas cerca de Ronda en julio.",
     {"Ronda": "Location", "julio": "Date"}),
    ("Bancolombia prevé una inflación del 5.4% para el próximo año.",
     {"Bancolombia": "Organization", "5.4%": "Percentage"}),
    ("El ebanista Sagarduy tardó tres meses en restaurar el arcón.",
     {"Sagarduy": "Person"}),
    ("Llegamos a Cartagena un domingo al mediodía, muertos de calor.",
     {"Cartagena": "Location", "domingo": "Date"}),
    ("La entrada del museo cuesta €12 y los lunes es gratis.",
     {"€12": "Money", "lunes": "Date"}),
    ("Ecopetrol suspendió el bombeo por el atentado contra el oleoducto.",
     {"Ecopetrol": "Organization"}),
    ("La maestra Hortensia Valdivieso enseñó a leer a tres generaciones "
     "del pueblo.",
     {"Hortensia": "Person", "Valdivieso": "Person"}),
    ("El mercado abre a las 7:30 y lo mejor vuela antes de las 9:00.",
     {"7:30": "Time", "9:00": "Time"}),
    ("Dos de cada tres encuestados en Rosario apoyan la peatonalización.",
     {"Rosario": "Location"}),
    ("CaixaBank cerró 300 oficinas rurales pese a las protestas.",
     {"CaixaBank": "Organization"}),
    ("El temporal dejó olas de seis metros en la costa de Asturias el "
     "2023-01-17.",
     {"Asturias": "Location", "2023-01-17": "Date"}),
    ("El traductor Belaúnde trabajó veinte años en Ginebra sin aprender "
     "francés.",
     {"Belaúnde": "Person", "Ginebra": "Location"}),
    ("Vendimos la cosecha entera a una cooperativa de Logroño.",
     {"Logroño": "Location"}),
    ("El recibo de la luz subió un 18% respecto a febrero.",
     {"18%": "Percentage", "febrero": "Date"}),
    ("Panamá y Colombia reabrieron el paso fronterizo el miércoles.",
     {"Panamá": "Location", "Colombia": "Location", "miércoles": "Date"}),
    ("La impresora lleva atascada desde las 10:40 y el informe era para "
     "hoy.",
     {"10:40": "Time"}),
    ("Ferrovial adjudicó la obra del tranvía de Cuenca a su filial "
     "polaca.",
     {"Ferrovial": "Organization", "Cuenca": "Location"}),
    ("Mi vecino Arquímedes cría palomas mensajeras en la azotea.",
     {"Arquímedes": "Person"}),
    ("El vuelo de Iberia a Asunción se canceló por la ceniza del volcán.",
     {"Iberia": "Organization", "Asunción": "Location"}),
    ("La subasta del cuadro alcanzó €2,750,000 en apenas ocho minutos.",
     {"€2,750,000": "Money"}),
    ("El puerto de Bilbao movió un 7% más de contenedores en 2022.",
     {"Bilbao": "Location", "7%": "Percentage", "2022": "Date"}),
    ("La forense Izaguirre firmó el informe a las 3:55 de la madrugada.",
     {"Izaguirre": "Person", "3:55": "Time"}),
    ("Llevo desde agosto esperando la pieza del lavavajillas.",
     {"agosto": "Date"}),
    ("Cabify dejó de operar en Montevideo tras el cambio normativo.",
     {"Cabify": "Organization", "Montevideo": "Location"}),
    ("El cartero nuevo confunde la calle Espronceda con la avenida "
     "Esparteros.",
     {"Espronceda": "Location", "Esparteros": "Location"}),
    ("Crecimos un 11% en ventas y aun así cerraron la delegación de "
     "Murcia.",
     {"11%": "Percentage", "Murcia": "Location"}),
    ("El violinista Szeryng tocó en Guanajuato bajo la lluvia.",
     {"Szeryng": "Person", "Guanajuato": "Location"}),
    ("La reserva del parador cuesta €145 la noche en temporada alta.",
     {"€145": "Money"}),
    ("El simulacro de incendio será el jueves a las 12:15.",
     {"jueves": "Date", "12:15": "Time"}),
    ("Arcelor paró el alto horno de Avilés por mantenimiento.",
     {"Arcelor": "Organization", "Avilés": "Location"}),
    ("La señora Eulogia juraba haber visto al lobo junto al molino.",
     {"Eulogia": "Person"}),
    ("De Tarifa a Tánger hay apenas una hora de ferry.",
     {"Tarifa": "Location", "Tánger": "Location"}),
    ("El bono social descuenta un 25% a las familias numerosas.",
     {"25%": "Percentage"}),
    ("Entregamos el proyecto el 30/09/2025 tras dos prórrogas.",
     {"30/09/2025": "Date"}),
    ("El chef Arzak probó el guiso y pidió la receta a la abuela "
     "Casimira.",
     {"Arzak": "Person", "Casimira": "Person"}),
    ("Softtek contrató a doscientos ingenieros en Guadalajara.",
     {"Softtek": "Organization", "Guadalajara": "Location"}),
    ("La marea dejó el pecio al descubierto frente a Finisterre.",
     {"Finisterre": "Location"}),
    ("Pagué €35 por un paraguas que se rompió el mismo sábado.",
     {"€35": "Money", "sábado": "Date"}),
    ("El astrónomo Oterma calculó la órbita desde un tejado de "
     "Montevideo.",
     {"Oterma": "Person", "Montevideo": "Location"}),
    ("Las obras del metro de Quito avanzan al 85% según el consorcio.",
     {"Quito": "Location", "85%": "Percentage"}),
    ("El herrero Eustaquio Zabala forjó la veleta del campanario en "
     "1931.",
     {"Eustaquio": "Person", "Zabala": "Person", "1931": "Date"}),
]
