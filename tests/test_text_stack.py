"""Text stack tests (SURVEY §2.7): tokenizer + language detect, hashing TF,
count vectorizer, n-grams, similarities, domain parsers, MIME sniffing."""

import base64

import numpy as np
import pytest

from transmogrifai_tpu.ops.domains import (
    EmailToPickList,
    MimeTypeDetector,
    PhoneNumberValidator,
    UrlToDomainTransformer,
    ValidEmailTransformer,
    ValidUrlTransformer,
    detect_mime_type,
    parse_phone,
)
from transmogrifai_tpu.ops.text import (
    CountVectorizer,
    HashingTF,
    JaccardSimilarity,
    NGramSimilarity,
    NGramTransformer,
    StopWordsRemover,
    TextLenTransformer,
    TextTokenizer,
)
from transmogrifai_tpu.testkit import (
    TestFeatureBuilder,
    assert_estimator_spec,
    assert_transformer_spec,
)
from transmogrifai_tpu.types import (
    Base64,
    Email,
    MultiPickList,
    Phone,
    Text,
    TextList,
    URL,
)
from transmogrifai_tpu.utils.text import detect_language


class TestTokenizer:
    def test_basic_tokenize(self):
        f, ds = TestFeatureBuilder.of("t", Text, ["Hello, World! 42", None])
        stage = TextTokenizer()
        stage.set_input(f)
        out = assert_transformer_spec(stage, ds, expected=[["hello", "world", "42"], []])

    def test_stopword_removal_auto_language(self):
        f, ds = TestFeatureBuilder.of("t", Text, [
            "the cat sat on the mat and the dog",
            "el gato se sienta en la alfombra y el perro",
        ])
        stage = TextTokenizer(remove_stop_words=True)
        stage.set_input(f)
        out = stage.transform(ds)[stage.output_name]
        assert "the" not in out.data[0] and "cat" in out.data[0]
        assert "el" not in out.data[1] and "gato" in out.data[1]

    def test_detect_language(self):
        assert detect_language("the quick brown fox jumps over the lazy dog") == "en"
        assert detect_language("le chat est sur la table avec le chien") == "fr"
        assert detect_language("der Hund und die Katze sind nicht im Haus") == "de"
        assert detect_language("") == "unknown"


class TestVectorizers:
    def test_hashing_tf(self):
        f, ds = TestFeatureBuilder.of("toks", TextList,
                                      [["a", "b", "a"], [], ["c"]])
        stage = HashingTF(num_features=32)
        stage.set_input(f)
        out = assert_transformer_spec(stage, ds, check_row_parity=False)
        assert out.data.shape == (3, 32)
        assert out.data[0].sum() == 3.0  # counts, duplicate 'a' counted twice
        assert out.data[1].sum() == 0.0

    def test_hashing_tf_binary(self):
        f, ds = TestFeatureBuilder.of("toks", TextList, [["a", "a", "a"]])
        stage = HashingTF(num_features=16, binary=True)
        stage.set_input(f)
        out = stage.transform(ds)[stage.output_name]
        assert out.data[0].sum() == 1.0

    def test_count_vectorizer_vocab(self):
        f, ds = TestFeatureBuilder.of(
            "toks", TextList,
            [["apple", "banana"], ["apple"], ["apple", "cherry"], []])
        est = CountVectorizer(vocab_size=2, min_count=1)
        est.set_input(f)
        model = assert_estimator_spec(est, ds, check_row_parity=False)
        assert model.vocab[0] == "apple"  # most frequent first
        assert len(model.vocab) == 2
        out = model.transform(ds)[model.output_name]
        meta_names = [c.indicator_value for c in out.meta.columns]
        assert "apple" in meta_names

    def test_count_vectorizer_min_count(self):
        f, ds = TestFeatureBuilder.of(
            "toks", TextList, [["x", "y"], ["x"], ["x"]])
        est = CountVectorizer(min_count=2)
        est.set_input(f)
        model = est.fit(ds)
        assert model.vocab == ["x"]


class TestNGramsAndSimilarity:
    def test_ngram_transformer(self):
        f, ds = TestFeatureBuilder.of("toks", TextList, [["a", "b", "c"], ["a"]])
        stage = NGramTransformer(n=2)
        stage.set_input(f)
        assert_transformer_spec(stage, ds, expected=[["a b", "b c"], []])

    def test_stopwords_remover(self):
        f, ds = TestFeatureBuilder.of("toks", TextList, [["the", "cat"], None])
        stage = StopWordsRemover(language="en")
        stage.set_input(f)
        assert_transformer_spec(stage, ds, expected=[["cat"], []])

    def test_text_len(self):
        f, ds = TestFeatureBuilder.of("t", Text, ["abc", None, ""])
        stage = TextLenTransformer()
        stage.set_input(f)
        assert_transformer_spec(stage, ds, expected=[3, 0, 0])

    def test_ngram_similarity(self):
        feats, ds = TestFeatureBuilder.build(
            {"a": ["hamburger", "abc", None], "b": ["hamburgers", "xyz", "q"]},
            {"a": Text, "b": Text})
        stage = NGramSimilarity(n=3)
        stage.set_input(feats["a"], feats["b"])
        out = stage.transform(ds)[stage.output_name]
        vals = out.to_values()
        assert vals[0] > 0.7      # near-identical strings
        assert vals[1] == 0.0     # disjoint
        assert vals[2] == 0.0     # null side

    def test_jaccard_similarity(self):
        feats, ds = TestFeatureBuilder.build(
            {"a": [{"x", "y"}, set(), {"p"}], "b": [{"y", "z"}, set(), {"q"}]},
            {"a": MultiPickList, "b": MultiPickList})
        stage = JaccardSimilarity()
        stage.set_input(feats["a"], feats["b"])
        out = stage.transform(ds)[stage.output_name]
        vals = out.to_values()
        assert vals[0] == pytest.approx(1 / 3)
        assert vals[1] == 1.0     # both empty -> identical (reference semantics)
        assert vals[2] == 0.0


class TestDomainParsers:
    def test_phone_validity(self):
        assert parse_phone("(650) 555-1234", "US") is True
        assert parse_phone("123", "US") is False
        assert parse_phone("+1 650 555 1234", "GB") is True   # intl prefix wins
        assert parse_phone(None, "US") is None

    def test_phone_stage(self):
        f, ds = TestFeatureBuilder.of("p", Phone,
                                      ["650-555-1234", "12", None])
        stage = PhoneNumberValidator(default_region="US")
        stage.set_input(f)
        assert_transformer_spec(stage, ds, expected=[True, False, None])

    def test_email(self):
        f, ds = TestFeatureBuilder.of(
            "e", Email, ["a.b@example.com", "not-an-email", None])
        v = ValidEmailTransformer()
        v.set_input(f)
        assert_transformer_spec(v, ds, expected=[True, False, None])
        d = EmailToPickList()
        d.set_input(f)
        assert_transformer_spec(d, ds, expected=["example.com", None, None])

    def test_url(self):
        f, ds = TestFeatureBuilder.of(
            "u", URL, ["https://Docs.Example.com/x?q=1", "nope", None])
        v = ValidUrlTransformer()
        v.set_input(f)
        assert_transformer_spec(v, ds, expected=[True, False, None])
        d = UrlToDomainTransformer()
        d.set_input(f)
        assert_transformer_spec(d, ds, expected=["docs.example.com", None, None])

    def test_mime_detection(self):
        pdf = base64.b64encode(b"%PDF-1.4 rest of doc").decode()
        png = base64.b64encode(b"\x89PNG\r\n\x1a\n123").decode()
        txt = base64.b64encode(b"plain old text").decode()
        assert detect_mime_type(pdf) == "application/pdf"
        assert detect_mime_type(png) == "image/png"
        assert detect_mime_type(txt) == "text/plain"
        assert detect_mime_type("!!!notbase64!!!") is None
        f, ds = TestFeatureBuilder.of("b", Base64, [pdf, png, None])
        stage = MimeTypeDetector()
        stage.set_input(f)
        assert_transformer_spec(
            stage, ds, expected=["application/pdf", "image/png", None])
