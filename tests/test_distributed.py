"""Multi-host bootstrap helpers (SURVEY §5.8) — single-host semantics."""

import os

import jax
import numpy as np
import pytest

from transmogrifai_tpu.parallel import distributed
from transmogrifai_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


class TestDistributed:
    def test_initialize_single_host_noop(self):
        distributed.initialize()  # must not raise on one process

    def test_process_info(self):
        info = distributed.process_info()
        assert info["processCount"] == 1
        assert info["globalDevices"] == 8
        assert info["localDevices"] == 8

    def test_global_mesh_axes(self):
        mesh = distributed.global_mesh(n_model=2)
        assert mesh.shape[DATA_AXIS] == 4
        assert mesh.shape[MODEL_AXIS] == 2

    def test_host_local_rows_partition(self):
        s = distributed.host_local_rows(100)
        assert (s.start, s.stop) == (0, 100)  # single process owns all rows

    def test_host_local_rows_multiprocess_math(self):
        # simulate the partition arithmetic for 3 processes over 10 rows
        import transmogrifai_tpu.parallel.distributed as d

        orig_idx, orig_cnt = jax.process_index, jax.process_count
        try:
            jax.process_count = lambda: 3
            spans = []
            for pid in range(3):
                jax.process_index = lambda p=pid: p
                s = d.host_local_rows(10)
                spans.append((s.start, s.stop))
        finally:
            jax.process_index, jax.process_count = orig_idx, orig_cnt
        assert spans == [(0, 4), (4, 8), (8, 10)]
        assert sum(b - a for a, b in spans) == 10


class TestFailHardOnMultiWorkerMarkers:
    """ADVICE r1: auto-bootstrap failure on a marked multi-worker pod must raise,
    not degrade to N duplicate single-host runs."""

    def test_implied_worker_count(self, monkeypatch):
        from transmogrifai_tpu.parallel.distributed import _implied_worker_count

        for var in ("TPU_WORKER_HOSTNAMES", "SLURM_JOB_NUM_NODES",
                    "OMPI_COMM_WORLD_SIZE", "TPU_WORKER_ID",
                    "CLOUD_TPU_TASK_ID", "MEGASCALE_COORDINATOR_ADDRESS"):
            monkeypatch.delenv(var, raising=False)
        assert _implied_worker_count() == 1
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host1,host2,host3")
        assert _implied_worker_count() == 3
        monkeypatch.setenv("SLURM_JOB_NUM_NODES", "5")
        assert _implied_worker_count() == 5

    def test_implied_worker_count_index_and_megascale_markers(self, monkeypatch):
        """Every marker _pod_environment recognizes must feed the count: a
        worker index of k implies >= k+1 workers; megascale implies multislice."""
        from transmogrifai_tpu.parallel.distributed import _implied_worker_count

        for var in ("TPU_WORKER_HOSTNAMES", "SLURM_JOB_NUM_NODES",
                    "OMPI_COMM_WORLD_SIZE", "TPU_WORKER_ID",
                    "CLOUD_TPU_TASK_ID", "MEGASCALE_COORDINATOR_ADDRESS"):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        assert _implied_worker_count() == 1  # worker 0 alone is ambiguous
        monkeypatch.setenv("TPU_WORKER_ID", "3")
        assert _implied_worker_count() == 4
        monkeypatch.delenv("TPU_WORKER_ID")
        monkeypatch.setenv("CLOUD_TPU_TASK_ID", "2")
        assert _implied_worker_count() == 3
        monkeypatch.delenv("CLOUD_TPU_TASK_ID")
        monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "10.0.0.1:8476")
        assert _implied_worker_count() == 2

    def test_bootstrap_failure_raises_when_multiworker(self, monkeypatch):
        import jax

        from transmogrifai_tpu.parallel import distributed as D

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "a,b")

        def boom(**kw):
            raise RuntimeError("no coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        import pytest

        with pytest.raises(RuntimeError, match="imply 2 workers"):
            D.initialize()

    def test_bootstrap_failure_warns_when_single(self, monkeypatch, caplog):
        import jax

        from transmogrifai_tpu.parallel import distributed as D

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "onlyhost")
        monkeypatch.delenv("SLURM_JOB_NUM_NODES", raising=False)
        monkeypatch.delenv("OMPI_COMM_WORLD_SIZE", raising=False)

        def boom(**kw):
            raise RuntimeError("no coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        D.initialize()  # must not raise for a 1-host slice


class TestTwoProcessExecution:
    """Multi-process execution coverage (ISSUE 15 satellite): re-enabled
    STRUCTURALLY — the single-process tests below drive the real
    global-array assembly seam (``global_row_array`` + ``host_row_span``
    arithmetic) under mocked ``process_index``/``process_count``, the same
    pattern ``test_host_local_rows_multiprocess_math`` established; ONLY the
    true two-OS-process run (which needs multi-process CPU collectives the
    bundled jaxlib lacks) keeps its hardware xfail."""

    def test_assembly_path_single_process(self):
        """``global_row_array`` is the ingest seam every host calls with its
        decoded span; single-process it must produce exactly the placed
        global array (the logical array both paths define)."""
        from transmogrifai_tpu.parallel.mesh import make_mesh, use_mesh

        rng = np.random.default_rng(5)
        x = rng.normal(size=(48, 3)).astype(np.float32)
        with use_mesh(make_mesh()):
            g = distributed.global_row_array(x, n_global_rows=48)
            assert g.shape == (48, 3)
            shapes = {s.data.shape for s in g.addressable_shards}
            assert shapes == {(6, 3)}  # 48 rows / 8 devices on the data axis
            np.testing.assert_array_equal(np.asarray(g), x)
        # no mesh: plain placement, same logical array
        g2 = distributed.global_row_array(x)
        np.testing.assert_array_equal(np.asarray(g2), x)

    def test_assembly_arithmetic_two_mocked_hosts(self, monkeypatch):
        """The multi-process branch's contract, checked without a backend:
        each mocked host owns exactly its ``host_local_rows`` span, a
        wrong-sized block is refused with the span in the message, and the
        spans tile the global row range."""
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        n = 100
        spans = distributed.host_row_spans(n)
        assert [(s.start, s.stop) for s in spans] == [(0, 50), (50, 100)]
        for pid in range(2):
            monkeypatch.setattr(jax, "process_index", lambda p=pid: p)
            assert distributed.host_local_rows(n) == spans[pid]
        # the assembly entry refuses a block that is not this host's span
        from transmogrifai_tpu.parallel.mesh import make_mesh, use_mesh

        monkeypatch.setattr(jax, "process_index", lambda: 0)
        with use_mesh(make_mesh()):
            with pytest.raises(ValueError, match=r"rows \[0, 50\)"):
                distributed.global_row_array(
                    np.zeros((49, 3), np.float32), n_global_rows=100)

    def test_span_contributions_compose_to_global_stats(self):
        """The psum math the two-process worker exercises on hardware,
        decomposed over spans: per-span moment/correlation contributions
        must sum exactly to the single-process statistics."""
        rng = np.random.default_rng(0)
        x = rng.integers(-3, 4, size=(1024, 8)).astype(np.float64)
        spans = distributed.host_row_spans(1024, 2)
        total = sum(x[s].sum(axis=0) for s in spans)
        sq = sum((x[s] ** 2).sum(axis=0) for s in spans)
        np.testing.assert_array_equal(total, x.sum(axis=0))
        np.testing.assert_array_equal(sq, (x ** 2).sum(axis=0))
        mean = total / 1024
        var = sq / 1024 - mean ** 2
        np.testing.assert_allclose(var, x.var(axis=0), rtol=1e-12)

    @pytest.mark.xfail(
        strict=False,
        reason="pre-existing at seed HEAD on this container: the bundled "
               "jaxlib CPU backend raises 'Multiprocess computations aren't "
               "implemented on the CPU backend' inside the workers; passes "
               "on real multi-host slices — tracked in ROADMAP Open items")
    def test_two_process_column_stats_match_single_process(self, tmp_path):
        import json
        import socket
        import subprocess
        import sys

        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "distributed_worker.py")
        with socket.socket() as s:  # pick a free port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        out = str(tmp_path / "stats.json")
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        procs = [subprocess.Popen(
            [sys.executable, worker, str(i), str(port), out],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
            for i in range(2)]
        outputs = []
        for p in procs:
            try:
                stdout, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outputs.append(stdout.decode())
        for i, (p, text) in enumerate(zip(procs, outputs)):
            assert p.returncode == 0, f"worker {i} failed:\n{text[-2000:]}"
            assert f"WORKER_OK {i}" in text

        got = json.load(open(out))
        assert got["info"]["processCount"] == 2
        assert got["info"]["globalDevices"] == 4

        # single-process reference on the same data
        rng = np.random.default_rng(0)
        x = rng.normal(size=(1024, 8)).astype(np.float32)
        y = (rng.random(1024) < 0.5).astype(np.float32)
        xc = x - x.mean(0)
        yc = y - y.mean()
        corr = (xc * yc[:, None]).mean(0) / np.maximum(
            xc.std(0) * yc.std(), 1e-12)
        np.testing.assert_allclose(got["mean"], x.mean(0), atol=1e-5)
        np.testing.assert_allclose(got["var"], x.var(0), atol=1e-5)
        np.testing.assert_allclose(got["corr"], corr, atol=1e-4)

        # GBT parity (VERDICT r4 #7): the process-separated fit — histogram
        # psums crossing the two OS processes — must produce the same trees
        # and margins as a single-process fit on the same rows
        import jax
        import jax.numpy as jnp

        from transmogrifai_tpu.models.trees import _fit_gbt

        n_bins = 8
        binned = rng.integers(0, n_bins + 1, size=(1024, 8)).astype(np.int32)
        w = np.ones(1024, np.float32)
        margin, trees = _fit_gbt(
            jnp.asarray(binned), jnp.asarray(y), jnp.asarray(w),
            jax.random.PRNGKey(7), n_rounds=2, max_depth=2, n_bins=n_bins,
            objective="binary:logistic", num_class=1, subsample=1.0,
            colsample_bytree=1.0, colsample_bylevel=1.0,
            eta=jnp.float32(0.3), reg_lambda=jnp.float32(1.0),
            alpha=jnp.float32(0.0), gamma=jnp.float32(0.0),
            min_child_weight=jnp.float32(1.0),
            scale_pos_weight=jnp.float32(1.0),
            max_delta_step=jnp.float32(0.0),
            base_score=jnp.zeros(1, jnp.float32))
        ref = {k: np.asarray(v) for k, v in trees._asdict().items()}
        got_trees = got["gbt_trees"]
        # split structure must match EXACTLY; values/margins to float tol
        np.testing.assert_array_equal(got_trees["feat"], ref["feat"])
        np.testing.assert_array_equal(got_trees["thr_bin"], ref["thr_bin"])
        np.testing.assert_array_equal(got_trees["is_leaf"], ref["is_leaf"])
        np.testing.assert_allclose(got_trees["value"], ref["value"],
                                   atol=1e-4)
        np.testing.assert_allclose(got["gbt_margin_sum"],
                                   float(np.asarray(margin).sum()), rtol=1e-3)
