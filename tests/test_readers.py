"""Reader tests: custom/aggregate/conditional readers and typed joins.

Reference test model: readers module suites (SURVEY §2.5, §4) — DataReader row
generation, aggregate readers' leakage-safe cutoff semantics, and
JoinedDataReader joins (JoinedDataReader.scala:1-442).
"""

import numpy as np
import pytest

from transmogrifai_tpu.aggregators.monoid import CutOffTime
from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.readers.base import (
    AggregateReader,
    ConditionalReader,
    CustomReader,
)
from transmogrifai_tpu.readers.joined import (
    JoinedReader,
    JoinType,
    TimeBasedFilter,
    TimeColumn,
)


def people_features():
    name = FeatureBuilder.Text("name").extract(lambda r: r["name"]).as_predictor()
    age = FeatureBuilder.Real("age").extract(lambda r: r["age"]).as_predictor()
    return name, age


PEOPLE = [
    {"id": "a", "name": "ann", "age": 30.0},
    {"id": "b", "name": "bob", "age": 40.0},
    {"id": "c", "name": "cat", "age": 50.0},
]

PURCHASES = [
    {"id": "a", "amount": 10.0, "t": 100},
    {"id": "a", "amount": 5.0, "t": 200},
    {"id": "b", "amount": 7.0, "t": 150},
    {"id": "d", "amount": 99.0, "t": 300},
]


class TestAggregateReaders:
    def test_aggregate_sums_events_per_key(self):
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        reader = AggregateReader(
            CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"]),
            key_fn=lambda r: r["id"], time_fn=lambda r: r["t"])
        ds = reader.generate_dataset([amount])
        # keys sorted: a, b, d — amounts monoid-summed per key
        assert ds["amount"].to_values() == [15.0, 7.0, 99.0]

    def test_aggregate_cutoff_excludes_late_predictors(self):
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        reader = AggregateReader(
            CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"]),
            key_fn=lambda r: r["id"], time_fn=lambda r: r["t"],
            cutoff=CutOffTime.unix(150))
        ds = reader.generate_dataset([amount])
        # predictors fold events strictly before t=150: a keeps t=100 only, b none
        vals = ds["amount"].to_values()
        assert vals[0] == 10.0
        assert vals[1] is None  # empty aggregate stays empty, not zero-filled

    def test_conditional_reader_drops_keys_without_condition(self):
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        reader = ConditionalReader(
            CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"]),
            key_fn=lambda r: r["id"], time_fn=lambda r: r["t"],
            condition_fn=lambda r: r["amount"] < 8.0)
        ds = reader.generate_dataset([amount])
        # only keys a (amount 5 @200) and b (7 @150) have a condition event
        assert ds.n_rows == 2


class TestJoinedReader:
    def make_readers(self):
        left = CustomReader(lambda: PEOPLE, key_fn=lambda r: r["id"])
        right = CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"])
        return left, right

    def features(self):
        name, age = people_features()
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        return name, age, amount

    def test_inner_join_duplicates_left_rows(self):
        name, age, amount = self.features()
        left, right = self.make_readers()
        ds = JoinedReader(left, right, ["name", "age"],
                          JoinType.INNER).generate_dataset([name, age, amount])
        # a matches 2 purchases, b matches 1, c none, d unmatched-right dropped
        assert ds.n_rows == 3
        assert sorted(ds["name"].to_values()) == ["ann", "ann", "bob"]
        assert sorted(ds["amount"].to_values()) == [5.0, 7.0, 10.0]

    def test_left_outer_fills_missing_right(self):
        name, age, amount = self.features()
        left, right = self.make_readers()
        ds = JoinedReader(left, right, ["name", "age"],
                          JoinType.LEFT_OUTER).generate_dataset([name, age, amount])
        assert ds.n_rows == 4  # c kept with empty amount
        rows = list(zip(ds["name"].to_values(), ds["amount"].to_values()))
        assert ("cat", None) in rows

    def test_full_outer_keeps_unmatched_right(self):
        name, age, amount = self.features()
        left, right = self.make_readers()
        ds = JoinedReader(left, right, ["name", "age"],
                          JoinType.FULL_OUTER).generate_dataset([name, age, amount])
        assert ds.n_rows == 5  # + unmatched right key d
        rows = list(zip(ds["name"].to_values(), ds["amount"].to_values()))
        assert (None, 99.0) in rows

    def test_missing_key_fn_raises(self):
        name, age, amount = self.features()
        left = CustomReader(lambda: PEOPLE)  # no key_fn
        right = CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"])
        with pytest.raises(ValueError, match="key_fn"):
            JoinedReader(left, right, ["name", "age"]).generate_dataset(
                [name, age, amount])

    def test_join_with_conditional_right_side(self):
        """Readers that drop keys (ConditionalReader) join on their kept keys only."""
        name, age, amount = self.features()
        left, _ = self.make_readers()
        right = ConditionalReader(
            CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"]),
            key_fn=lambda r: r["id"], time_fn=lambda r: r["t"],
            condition_fn=lambda r: r["amount"] < 8.0)  # keeps keys a, b only
        ds = JoinedReader(left, right, ["name", "age"],
                          JoinType.LEFT_OUTER).generate_dataset([name, age, amount])
        assert ds.n_rows == 3
        rows = dict(zip(ds["name"].to_values(), ds["amount"].to_values()))
        assert rows["cat"] is None  # no conditional row for c

    def test_secondary_aggregation_requires_time_columns(self):
        name, age, amount = self.features()
        left, right = self.make_readers()
        reader = JoinedReader(left, right, ["name", "age"]).with_secondary_aggregation(
            TimeBasedFilter(condition=TimeColumn("signup"), primary=TimeColumn("t")))
        with pytest.raises(ValueError, match="time columns"):
            reader.generate_dataset([name, age, amount])

    def test_chained_left_deep_join(self):
        name, age, amount = self.features()
        visits = [{"id": "a", "visits": 3.0}, {"id": "c", "visits": 1.0}]
        nvisits = (FeatureBuilder.Real("visits")
                   .extract(lambda r: r["visits"]).as_predictor())
        left, right = self.make_readers()
        inner = JoinedReader(left, right, ["name", "age"], JoinType.LEFT_OUTER)
        outer = JoinedReader(
            inner, CustomReader(lambda: visits, key_fn=lambda r: r["id"]),
            ["name", "age", "amount"], JoinType.LEFT_OUTER)
        ds = outer.generate_dataset([name, age, amount, nvisits])
        assert ds.n_rows == 4
        rows = dict(zip(ds["name"].to_values(), ds["visits"].to_values()))
        assert rows["ann"] == 3.0 and rows["cat"] == 1.0 and rows["bob"] is None


class TestJoinedReaderRegressions:
    def test_one_sided_feature_request(self):
        """Requesting only left-side features must not crash (scoring subsets)."""
        name, age = people_features()
        left = CustomReader(lambda: PEOPLE, key_fn=lambda r: r["id"])
        right = CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"])
        ds = JoinedReader(left, right, ["name", "age"],
                          JoinType.INNER).generate_dataset([name, age])
        # inner-join row multiplicity still applies even with no right columns
        assert sorted(ds["name"].to_values()) == ["ann", "ann", "bob"]

    def test_typoed_left_feature_name_raises(self):
        name, age = people_features()
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        left = CustomReader(lambda: PEOPLE, key_fn=lambda r: r["id"])
        right = CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"])
        with pytest.raises(ValueError, match="typos"):
            JoinedReader(left, right, ["Name", "age"]).generate_dataset(
                [name, age, amount])

    def test_absent_left_names_tolerated_for_subsets(self):
        """Left names not in the request (scoring subsets) must not raise."""
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        left = CustomReader(lambda: PEOPLE, key_fn=lambda r: r["id"])
        right = CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"])
        ds = JoinedReader(left, right, ["name", "age"],
                          JoinType.INNER).generate_dataset([amount])
        assert sorted(ds["amount"].to_values()) == [5.0, 7.0, 10.0]

    def test_join_keeps_dataframe_reader_cleaning(self):
        """DataFrameReader sides keep their columnar NaN/dtype cleaning in joins."""
        import pandas as pd

        from transmogrifai_tpu.readers.base import DataFrameReader

        from transmogrifai_tpu.types import Integral

        age_int = (FeatureBuilder.of("age", Integral)
                   .extract_field().as_predictor())
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        df = pd.DataFrame({"id": ["a", "b", "c"], "age": [30, None, 50]})
        left = DataFrameReader(df, key_fn=lambda r: r["id"])
        right = CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"])
        ds = JoinedReader(left, right, ["age"],
                          JoinType.LEFT_OUTER).generate_dataset([age_int, amount])
        by = dict(zip(ds["age"].to_values(), ds["amount"].to_values()))
        # pandas upcasts int+NaN to float64; the join must still yield clean Integrals
        assert 30 in by and None in by
        assert all(isinstance(a, int) for a in ds["age"].to_values() if a is not None)

    def test_nested_aggregate_reader_still_aggregates(self):
        """A JoinedAggregateReader nested in an outer join must keep its cutoff."""
        name, age = people_features()
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        t = FeatureBuilder.Date("t").extract(lambda r: r["t"]).as_predictor()
        signup = (FeatureBuilder.Date("signup")
                  .extract(lambda r: r.get("signup")).as_predictor())
        nvisits = (FeatureBuilder.Real("visits")
                   .extract(lambda r: r["visits"]).as_predictor())
        people = [dict(p, signup=250) for p in PEOPLE]
        visits = [{"id": "a", "visits": 3.0}, {"id": "b", "visits": 2.0}]
        left = CustomReader(lambda: people, key_fn=lambda r: r["id"])
        right = CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"])
        agg = JoinedReader(
            left, right, ["name", "age", "signup"], JoinType.LEFT_OUTER,
        ).with_secondary_aggregation(TimeBasedFilter(
            condition=TimeColumn("signup"), primary=TimeColumn("t")))
        outer = JoinedReader(
            agg, CustomReader(lambda: visits, key_fn=lambda r: r["id"]),
            ["name", "age", "signup", "amount", "t"], JoinType.LEFT_OUTER)
        ds = outer.generate_dataset([name, age, signup, amount, t, nvisits])
        by_name = dict(zip(ds["name"].to_values(), ds["amount"].to_values()))
        # one row per key and NO post-cutoff leakage: ann keeps 10+5 (both < 250),
        # if aggregation were skipped ann would appear twice
        assert ds.n_rows == 3
        assert by_name["ann"] == 15.0 and by_name["bob"] == 7.0
        vis = dict(zip(ds["name"].to_values(), ds["visits"].to_values()))
        assert vis["ann"] == 3.0 and vis["cat"] is None


class TestJoinedAggregateReader:
    def test_secondary_aggregation_folds_child_rows(self):
        name, age = people_features()
        amount = (FeatureBuilder.Real("amount")
                  .extract(lambda r: r["amount"]).as_predictor())
        t = (FeatureBuilder.Date("t").extract(lambda r: r["t"]).as_predictor())
        cutoff = (FeatureBuilder.Date("signup")
                  .extract(lambda r: r.get("signup")).as_predictor())
        people = [dict(p, signup=250) for p in PEOPLE]
        left = CustomReader(lambda: people, key_fn=lambda r: r["id"])
        right = CustomReader(lambda: PURCHASES, key_fn=lambda r: r["id"])
        reader = JoinedReader(
            left, right, ["name", "age", "signup"], JoinType.LEFT_OUTER,
        ).with_secondary_aggregation(TimeBasedFilter(
            condition=TimeColumn("signup"), primary=TimeColumn("t", keep=False)))
        ds = reader.generate_dataset([name, age, amount, t, cutoff])
        assert "t" not in ds.names
        by_name = dict(zip(ds["name"].to_values(), ds["amount"].to_values()))
        # one row per key; a's two purchases (both before signup=250) summed
        assert ds.n_rows == 3
        assert by_name["ann"] == 15.0
        assert by_name["bob"] == 7.0
        assert by_name["cat"] is None


class TestMicroBatchStreaming:
    """DStream-role streaming (VERDICT r3 missing #4): micro-batch clock,
    checkpointed offsets with at-least-once replay, and backpressure."""

    @staticmethod
    def _raws():
        from transmogrifai_tpu import FeatureBuilder
        from transmogrifai_tpu.types import Real

        return [FeatureBuilder.of("v", Real).extract_field().as_predictor()]

    @staticmethod
    def _reader(source, ckpt=None, **kw):
        from transmogrifai_tpu.readers import MicroBatchStreamingReader

        # virtual clock: no real sleeping in tests
        t = [0.0]
        kw.setdefault("clock", lambda: t[0])
        kw.setdefault("sleep", lambda s: t.__setitem__(0, t[0] + s))
        kw.setdefault("batch_interval", 1.0)
        kw.setdefault("max_empty_polls", 1)
        return MicroBatchStreamingReader(source, checkpoint=ckpt, **kw), t

    def test_offsets_resume_after_commit(self, tmp_path):
        from transmogrifai_tpu.readers import ListSource, OffsetCheckpoint

        ckpt = OffsetCheckpoint(str(tmp_path / "offsets.json"))
        records = [{"v": float(i)} for i in range(10)]
        reader, _ = self._reader(ListSource(records, "s1"), ckpt,
                                 max_batch_records=4)
        seen = []
        for ds in reader.stream_datasets(self._raws()):
            seen.extend(np.asarray(ds["v"].data).tolist())
            reader.commit()
            if len(seen) >= 4:
                break  # "crash" after the first committed batch
        assert seen == [0.0, 1.0, 2.0, 3.0]

        # restart from the checkpoint: continues at offset 4, no replay
        reader2, _ = self._reader(ListSource(records, "s1"), ckpt,
                                  max_batch_records=4)
        rest = []
        for ds in reader2.stream_datasets(self._raws()):
            rest.extend(np.asarray(ds["v"].data).tolist())
            reader2.commit()
        assert rest == [4.0, 5.0, 6.0, 7.0, 8.0, 9.0]

    def test_uncommitted_batch_replays(self, tmp_path):
        from transmogrifai_tpu.readers import ListSource, OffsetCheckpoint

        ckpt = OffsetCheckpoint(str(tmp_path / "offsets.json"))
        records = [{"v": float(i)} for i in range(6)]
        reader, _ = self._reader(ListSource(records, "s2"), ckpt,
                                 max_batch_records=3)
        it = reader.stream_datasets(self._raws())
        next(it)  # first batch yielded but NEVER committed -> crash
        reader2, _ = self._reader(ListSource(records, "s2"), ckpt,
                                  max_batch_records=3)
        ds = next(reader2.stream_datasets(self._raws()))
        # at-least-once: the uncommitted batch is delivered again
        assert np.asarray(ds["v"].data).tolist() == [0.0, 1.0, 2.0]

    def test_backpressure_shrinks_then_recovers(self):
        from transmogrifai_tpu.readers import ListSource

        records = [{"v": float(i)} for i in range(4000)]
        reader, t = self._reader(ListSource(records, "s3"),
                                 max_batch_records=1024,
                                 min_batch_records=8)
        targets = []
        slow = [True, True, True, False, False, False]
        for i, ds in enumerate(reader.stream_datasets(self._raws())):
            if i < len(slow) and slow[i]:
                t[0] += 4.0  # consumer took 4x the batch interval
            targets.append(reader.progress["target_records"])
            reader.commit()
            if i >= 5:
                break
        # targets[i] is read BEFORE batch i resumes the generator, so it
        # reflects batch i-1's adjustment: slow batches shrink the target
        # geometrically, fast ones recover it
        assert targets[0] == 1024  # initial
        assert targets[1] < targets[0]
        assert targets[2] < targets[1]
        assert max(targets[4:]) > min(targets[1:4])

    def test_jsonl_tail_source_resumes_mid_file(self, tmp_path):
        import json

        from transmogrifai_tpu.readers import JsonlTailSource

        p = str(tmp_path / "events.jsonl")
        with open(p, "w") as fh:
            for i in range(5):
                fh.write(json.dumps({"v": i}) + "\n")
            fh.write('{"v": 99')  # partial trailing line (writer mid-append)
        src = JsonlTailSource(p)
        recs, off = src.poll(10)
        assert [r["v"] for r in recs] == [0, 1, 2, 3, 4]
        # the partial line was NOT consumed; complete it and poll again
        with open(p, "a") as fh:
            fh.write(', "w": 1}\n')
        src2 = JsonlTailSource(p)
        src2.seek(off)
        recs2, _ = src2.poll(10)
        assert recs2 == [{"v": 99, "w": 1}]

    def test_runner_streaming_commits_offsets(self, tmp_path):
        """End-to-end: the runner's streaming_score run commits offsets
        after each written batch (restart scores only new records)."""
        from transmogrifai_tpu import FeatureBuilder, Workflow
        from transmogrifai_tpu.data.dataset import Column, Dataset
        from transmogrifai_tpu.readers import (ListSource,
                                               MicroBatchStreamingReader,
                                               OffsetCheckpoint)
        from transmogrifai_tpu.types import Real, RealNN
        from transmogrifai_tpu.workflow.runner import (RunType,
                                                       WorkflowRunner)
        from transmogrifai_tpu.params import OpParams

        rng = np.random.default_rng(3)
        n = 300
        ds = Dataset({
            "v": Column.from_values(Real, rng.normal(size=n).tolist()),
            "label": Column.from_values(
                RealNN, (rng.random(n) > 0.5).astype(float).tolist())})
        label = FeatureBuilder.of("label", RealNN).extract_field() \
            .as_response()
        v = FeatureBuilder.of("v", Real).extract_field().as_predictor()
        pred = v.fill_missing_with_mean().z_normalize()
        model = Workflow().set_input_dataset(ds) \
            .set_result_features(pred).train()
        mdir = str(tmp_path / "model")
        model.save(mdir)

        ckpt = OffsetCheckpoint(str(tmp_path / "off.json"))
        stream_records = [{"v": float(i)} for i in range(7)]
        reader = MicroBatchStreamingReader(
            ListSource(stream_records, "run"), checkpoint=ckpt,
            batch_interval=0.0, max_batch_records=3, max_empty_polls=1)
        wf = Workflow().set_input_dataset(ds).set_result_features(pred)
        runner = WorkflowRunner(workflow=wf, streaming_reader=reader)
        result = runner.run(RunType.STREAMING_SCORE, OpParams(
            model_location=mdir,
            write_location=str(tmp_path / "scored")))
        assert result.metrics["batches"] == 3  # 3 + 3 + 1
        assert ckpt.load("run") == 7  # all offsets committed

    def test_jsonl_rotation_resets_and_bad_line_is_loud(self, tmp_path):
        import json

        from transmogrifai_tpu.readers import JsonlTailSource

        p = str(tmp_path / "rot.jsonl")
        with open(p, "w") as fh:
            for i in range(20):
                fh.write(json.dumps({"v": i}) + "\n")
        src = JsonlTailSource(p)
        _, off = src.poll(100)
        # rotation: the file is truncated and restarted smaller
        with open(p, "w") as fh:
            fh.write(json.dumps({"v": 100}) + "\n")
        src.seek(off)
        recs, _ = src.poll(10)
        assert [r["v"] for r in recs] == [100]  # reset to head, not stalled

        # malformed line: good prefix delivered, then the poison pill raises
        with open(p, "a") as fh:
            fh.write(json.dumps({"v": 101}) + "\n")
            fh.write("{not json}\n")
        recs2, off2 = src.poll(10)
        assert [r["v"] for r in recs2] == [101]
        src.seek(off2)
        with pytest.raises(ValueError, match="malformed JSONL"):
            src.poll(10)

    def test_jsonl_rotation_to_larger_file_resets(self, tmp_path):
        """Satellite regression: a rotated file that happens to be LONGER
        than the committed offset must restart from its head — the size
        heuristic alone would resume mid-file and silently skip records."""
        import json

        from transmogrifai_tpu.readers import JsonlTailSource

        p = str(tmp_path / "rot2.jsonl")
        with open(p, "w") as fh:
            for i in range(3):
                fh.write(json.dumps({"v": i}) + "\n")
        src = JsonlTailSource(p)
        recs, off = src.poll(100)
        assert [r["v"] for r in recs] == [0, 1, 2]

        # rotate: replace with a DIFFERENT, LONGER file (new inode and head)
        tmp = str(tmp_path / "rot2.jsonl.new")
        with open(tmp, "w") as fh:
            for i in range(100, 120):
                fh.write(json.dumps({"v": i}) + "\n")
        import os

        os.replace(tmp, p)
        assert os.path.getsize(p) > off  # the case the size check misses
        recs2, _ = src.poll(100)
        assert [r["v"] for r in recs2][:3] == [100, 101, 102], \
            "rotated-to-larger file must be read from its head"
        assert len(recs2) == 20

    def test_jsonl_copytruncate_rotation_same_inode(self, tmp_path):
        """In-place rewrite (copytruncate rotation) keeps the inode; the
        head-prefix heuristic must still catch it when the new file is
        longer than the committed offset."""
        import json

        from transmogrifai_tpu.readers import JsonlTailSource

        p = str(tmp_path / "rot3.jsonl")
        with open(p, "w") as fh:
            fh.write(json.dumps({"v": 1}) + "\n")
        src = JsonlTailSource(p)
        recs, off = src.poll(100)
        assert [r["v"] for r in recs] == [1]
        # rewrite in place (same path, same inode on most filesystems),
        # longer than the committed offset, different head bytes
        with open(p, "r+") as fh:
            for i in range(200, 210):
                fh.write(json.dumps({"value": i, "pad": "x" * 10}) + "\n")
        recs2, _ = src.poll(100)
        assert recs2 and recs2[0] == {"value": 200, "pad": "x" * 10}
        assert len(recs2) == 10

    def test_rotation_while_process_down_detected_via_checkpoint(
            self, tmp_path):
        """The rotation pins (inode + consumed head) persist BESIDE the
        committed offset: a file rotated to a LONGER one while the process
        was down is detected by the fresh reader and read from its head —
        without the persisted pins it would resume mid-file in the new
        file and silently skip its head records."""
        import json
        import os

        from transmogrifai_tpu.readers import (JsonlTailSource,
                                               MicroBatchStreamingReader,
                                               OffsetCheckpoint)

        p = str(tmp_path / "live.jsonl")
        with open(p, "w") as fh:
            for i in range(3):
                fh.write(json.dumps({"v": float(i)}) + "\n")
        cpath = str(tmp_path / "off.json")
        raws = self._raws()

        def fresh_reader():
            return MicroBatchStreamingReader(
                JsonlTailSource(p, source_id="live"),
                checkpoint=OffsetCheckpoint(cpath), batch_interval=0.0,
                max_batch_records=100, max_empty_polls=1)

        r1 = fresh_reader()
        it = r1.stream_datasets(raws)
        assert np.asarray(next(it)["v"].data).tolist() == [0.0, 1.0, 2.0]
        r1.commit()
        del it, r1  # process exits; offset + rotation pins are durable

        # while down: logrotate swaps in a NEW, LONGER file
        tmp = p + ".new"
        with open(tmp, "w") as fh:
            for i in range(100, 130):
                fh.write(json.dumps({"v": float(i)}) + "\n")
        os.replace(tmp, p)
        committed = OffsetCheckpoint(cpath).load("live")
        assert os.path.getsize(p) > committed  # size check alone is blind

        r2 = fresh_reader()
        got = []
        for ds in r2.stream_datasets(raws):
            got.extend(np.asarray(ds["v"].data).tolist())
            r2.commit()
        assert got[:3] == [100.0, 101.0, 102.0], \
            "rotated-while-down file must be read from its head"
        assert len(got) == 30

    def test_skip_malformed_mode_advances_past_poison_line(self, tmp_path):
        """Follow-mode regression: with skip_malformed=True a poison line
        sitting exactly at the committed offset is skipped-and-counted
        instead of raising forever at the same byte; default stays loud."""
        import json

        from transmogrifai_tpu.readers import JsonlTailSource

        p = str(tmp_path / "poison.jsonl")
        with open(p, "w") as fh:
            fh.write(json.dumps({"v": 1}) + "\n")
            fh.write("{not json}\n")
            fh.write(json.dumps({"v": 2}) + "\n")
        src = JsonlTailSource(p, skip_malformed=True)
        recs, off = src.poll(10)
        assert [r["v"] for r in recs] == [1]  # good prefix first
        src.seek(off)
        recs2, _ = src.poll(10)  # poison skipped, stream continues
        assert [r["v"] for r in recs2] == [2]
        assert src.skipped_malformed == 1
        # the loud default still raises at the same spot
        strict = JsonlTailSource(p)
        strict.seek(off)
        with pytest.raises(ValueError, match="malformed JSONL"):
            strict.poll(10)

    def test_offset_checkpoint_cleans_stale_tmp(self, tmp_path):
        """Satellite: a crash between writing the tmp file and the atomic
        rename leaves a stale .tmp that must not survive (or be mistaken
        for the store) on the next load; the committed store still reads."""
        from transmogrifai_tpu.readers import OffsetCheckpoint

        path = str(tmp_path / "off.json")
        ckpt = OffsetCheckpoint(path)
        ckpt.commit("s", 42)
        # simulated crash mid-commit: tmp written, rename never happened
        with open(path + ".tmp", "w") as fh:
            fh.write("{torn")
        assert ckpt.load("s") == 42
        import os

        assert not os.path.exists(path + ".tmp")

    def test_crash_replay_with_file_source_and_checkpoint(self, tmp_path):
        """Satellite: at-least-once over the DURABLE pair (JsonlTailSource +
        OffsetCheckpoint) across simulated process restarts — an uncommitted
        batch is re-polled by a fresh reader, a committed one is not, and
        the backpressure target recovers after the slow batches."""
        import json

        from transmogrifai_tpu.readers import (JsonlTailSource,
                                               MicroBatchStreamingReader,
                                               OffsetCheckpoint)

        p = str(tmp_path / "events.jsonl")
        with open(p, "w") as fh:
            for i in range(9):
                fh.write(json.dumps({"v": float(i)}) + "\n")
        cpath = str(tmp_path / "off.json")

        def fresh_reader(**kw):
            t = [0.0]
            return MicroBatchStreamingReader(
                JsonlTailSource(p, source_id="ev"),
                checkpoint=OffsetCheckpoint(cpath), batch_interval=1.0,
                max_batch_records=3, min_batch_records=1,
                max_empty_polls=1, clock=lambda: t[0],
                sleep=lambda s: t.__setitem__(0, t[0] + s), **kw), t

        raws = self._raws()
        # "process 1": consume one batch, commit, consume another, CRASH
        # before committing it
        r1, _ = fresh_reader()
        it = r1.stream_datasets(raws)
        b1 = np.asarray(next(it)["v"].data).tolist()
        r1.commit()
        b2 = np.asarray(next(it)["v"].data).tolist()
        assert (b1, b2) == ([0.0, 1.0, 2.0], [3.0, 4.0, 5.0])
        del it, r1  # crash: batch 2 never committed

        # "process 2": batch 2 replays (at-least-once), batch 1 does not
        r2, t2 = fresh_reader()
        seen = []
        slow = [True, True, False, False]
        targets = []
        for i, ds in enumerate(r2.stream_datasets(raws)):
            if i < len(slow) and slow[i]:
                t2[0] += 4.0  # slow consumer: shrink the target
            seen.extend(np.asarray(ds["v"].data).tolist())
            targets.append(r2.progress["target_records"])
            r2.commit()
        assert seen == [3.0, 4.0, 5.0, 6.0, 7.0, 8.0]
        # backpressure target shrank under the slow batches then recovered
        assert min(targets) < 3 and targets[-1] > min(targets)

    def test_dataframe_batch_without_label_scores(self, tmp_path):
        """Columnar (DataFrame) micro-batches may omit the response column at
        scoring time, same as record-iterator batches; a PRESENT-but-malformed
        label still raises (data-quality bugs stay loud)."""
        import pandas as pd

        from transmogrifai_tpu import FeatureBuilder, Workflow
        from transmogrifai_tpu.data.dataset import Column, Dataset
        from transmogrifai_tpu.readers.base import rows_to_dataset
        from transmogrifai_tpu.readers.files import StreamingReader
        from transmogrifai_tpu.types import Real, RealNN
        from transmogrifai_tpu.params import OpParams
        from transmogrifai_tpu.workflow.runner import RunType, WorkflowRunner

        rng = np.random.default_rng(5)
        n = 200
        ds = Dataset({
            "v": Column.from_values(Real, rng.normal(size=n).tolist()),
            "label": Column.from_values(
                RealNN, (rng.random(n) > 0.5).astype(float).tolist())})
        label = FeatureBuilder.of("label", RealNN).extract_field() \
            .as_response()
        v = FeatureBuilder.of("v", Real).extract_field().as_predictor()
        pred = v.fill_missing_with_mean().z_normalize()
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        model = wf.train()
        mdir = str(tmp_path / "m")
        model.save(mdir)

        df_no_label = pd.DataFrame({"v": rng.normal(size=7)})
        runner = WorkflowRunner(
            workflow=wf, streaming_reader=StreamingReader([df_no_label]))
        res = runner.run(RunType.STREAMING_SCORE,
                         OpParams(model_location=mdir))
        assert res.metrics["batches"] == 1
        assert len(np.asarray(res.scores[0][pred.name].data)) == 7

        # malformed PRESENT label in a record batch must still raise
        raws = [label, v]
        with pytest.raises(Exception):
            rows_to_dataset([{"v": 1.0, "label": "not-a-number"}], raws,
                            allow_missing_response=True)
