"""Continuous warm refit (ISSUE 9): drift-gated streaming retrain loop with
shadow scoring, atomic model swap, and rollback — every phase under the
deterministic fault harness.

Acceptance criteria proven here (TestContinualE2E):
- streamed batches with injected covariate drift fire the drift detector;
- the warm refit completes with ZERO new backend compiles on the transform
  prefix (frozen prep -> plan cache + sweep executable cache hits);
- the shadow parity gate passes and the atomic swap serves the new model
  with no dropped or double-scored in-flight requests;
- under injected refit/swap faults (FaultHarness scripts) the server keeps
  serving the last-known-good model;
- a post-swap circuit-breaker trip auto-rolls back to the retained
  last-known-good model.
"""

import json
import math
import os

import numpy as np
import pytest

from transmogrifai_tpu import (
    BinaryClassificationModelSelector,
    FeatureBuilder,
    Workflow,
    transmogrify,
)
from transmogrifai_tpu.checkers.diagnostics import OpCheckError
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.perf import measure_compiles
from transmogrifai_tpu.readers import ListSource, MicroBatchStreamingReader
from transmogrifai_tpu.readers.base import rows_to_dataset
from transmogrifai_tpu.readers.files import DataReaders
from transmogrifai_tpu.serve import (
    FaultHarness,
    ScoringServer,
    TransientScoringError,
    prediction_delta,
)
from transmogrifai_tpu.workflow.continual import (
    ContinualTrainer,
    DriftDetector,
    PromotionGate,
    RefitController,
    RefitError,
    TrainingSnapshot,
)
from transmogrifai_tpu.workflow.workflow import dedup_raw_features

N_TRAIN = 256


def make_records(n, seed, shift=0.0, missing_rate=0.0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 3)) + shift
    out = []
    for i in range(n):
        rec = {"label": float(r.random() < 1 / (1 + np.exp(-x[i, 0])))}
        for j in range(3):
            rec[f"num{j}"] = None if r.random() < missing_rate \
                else float(x[i, j])
        out.append(rec)
    return out


@pytest.fixture(scope="module")
def base():
    """(model, train records, raw features, train dataset, snapshot)."""
    import pandas as pd

    train = make_records(N_TRAIN, 1)
    label = FeatureBuilder.RealNN("label").extract_field().as_response()
    feats = [FeatureBuilder.Real(f"num{j}").extract_field().as_predictor()
             for j in range(3)]
    checked = label.sanity_check(transmogrify(feats))
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        models=[(LogisticRegression(), [{"reg_param": 0.01}])])
    pred = label.transform_with(sel, checked)
    model = (Workflow().set_result_features(label, pred)
             .set_reader(DataReaders.Simple.dataframe(pd.DataFrame(train)))
             ).train()
    raws = dedup_raw_features(model.result_features)
    train_ds = rows_to_dataset(train, raws)
    snap = TrainingSnapshot.from_dataset(train_ds, features=raws)
    return model, train, raws, train_ds, snap


def stream_reader(records, batch=128):
    return MicroBatchStreamingReader(
        ListSource(records, "stream"), batch_interval=0.0,
        max_batch_records=batch, max_empty_polls=1)


# ---------------------------------------------------------------------------
# Drift detection
# ---------------------------------------------------------------------------

class TestDriftDetector:
    def test_snapshot_covers_numeric_predictors_only(self, base):
        *_, snap = base
        assert sorted(snap.features) == ["num0", "num1", "num2"]
        assert snap.n_rows == N_TRAIN
        for fs in snap.features.values():
            assert len(fs.bin_probs) == len(fs.bin_edges) + 1
            assert abs(sum(fs.bin_probs) - 1.0) < 1e-9

    def test_snapshot_roundtrip(self, base, tmp_path):
        *_, snap = base
        p = str(tmp_path / "snap.json")
        snap.save(p)
        loaded = TrainingSnapshot.load(p)
        assert loaded.to_dict() == snap.to_dict()

    def test_quiet_on_same_distribution(self, base):
        model, train, raws, train_ds, snap = base
        det = DriftDetector(snap, min_records=128)
        det.observe(rows_to_dataset(make_records(512, 9), raws))
        report = det.evaluate()
        assert not DriftDetector.drifted(report), [d.pretty() for d in report]

    def test_insufficient_data_defers_tm804(self, base):
        *_, snap = base
        det = DriftDetector(snap, min_records=128)
        report = det.evaluate()
        assert [d.code for d in report] == ["TM804"]
        assert not DriftDetector.drifted(report)

    def test_covariate_shift_fires_psi_and_z(self, base):
        model, train, raws, train_ds, snap = base
        det = DriftDetector(snap, min_records=128)
        det.observe(rows_to_dataset(make_records(512, 10, shift=3.0), raws))
        report = det.evaluate()
        codes = {d.code for d in report}
        assert "TM801" in codes and "TM802" in codes
        assert DriftDetector.drifted(report)
        stats = det.feature_stats()
        assert stats["num0"]["psi"] > det.psi_threshold

    def test_missing_rate_shift_fires_tm803(self, base):
        model, train, raws, train_ds, snap = base
        det = DriftDetector(snap, min_records=128)
        det.observe(rows_to_dataset(
            make_records(512, 11, missing_rate=0.6), raws))
        report = det.evaluate()
        assert any(d.code == "TM803" for d in report)

    def test_total_outage_all_missing_still_fires_tm803(self, base):
        """A TOTAL upstream outage (every value missing) must still raise
        the missing-rate alarm — PSI/z need valid values, TM803 does not."""
        model, train, raws, train_ds, snap = base
        det = DriftDetector(snap, min_records=128)
        det.observe(rows_to_dataset(
            make_records(256, 13, missing_rate=1.0), raws))
        report = det.evaluate()
        assert any(d.code == "TM803" for d in report)
        assert DriftDetector.drifted(report)
        stats = det.feature_stats()
        assert stats["num0"]["missing_rate"] == 1.0
        assert stats["num0"]["records"] == 0

    def test_constant_feature_shifted_to_new_constant_fires(self, base):
        """A feature constant in training (zero variance, collapsed bins)
        that shifts to a DIFFERENT constant must still fire: se == 0 with a
        moved mean is infinitely significant (TM802), not z = 0."""
        model, train, raws, train_ds, snap = base
        import copy

        snap2 = copy.deepcopy(snap)
        fs = snap2.features["num0"]
        fs.mean, fs.variance = 0.0, 0.0
        fs.bin_edges, fs.bin_probs = [0.0], [0.0, 1.0]
        det = DriftDetector(snap2, min_records=128)
        shifted = [{"label": 0.0, "num0": 5.0, "num1": 0.0, "num2": 0.0}
                   for _ in range(200)]
        det.observe(rows_to_dataset(shifted, raws,
                                    allow_missing_response=True))
        report = det.evaluate()
        assert any(d.code == "TM802" for d in report), \
            [d.pretty() for d in report]
        assert math.isinf(det.feature_stats()["num0"]["z"])
        # identical constant stays quiet
        det.reset()
        det.observe(rows_to_dataset(
            [{"label": 0.0, "num0": 0.0, "num1": 0.0, "num2": 0.0}
             for _ in range(200)], raws, allow_missing_response=True))
        assert det.feature_stats()["num0"]["z"] == 0.0

    def test_rebase_resets_accumulators(self, base):
        model, train, raws, train_ds, snap = base
        det = DriftDetector(snap, min_records=128)
        det.observe(rows_to_dataset(make_records(256, 12, shift=3.0), raws))
        assert det.records == 256
        det.rebase(snap)
        assert det.records == 0
        assert [d.code for d in det.evaluate()] == ["TM804"]


# ---------------------------------------------------------------------------
# Warm refit
# ---------------------------------------------------------------------------

class TestRefitController:
    def test_frozen_prefix_refit_zero_compiles(self, base):
        """Acceptance: after the one-time prime, a warm refit on a window of
        the training bucket performs ZERO backend compiles — the fused
        transform prefix comes back from the plan cache and the selector
        sweep from the content-addressed executable cache."""
        model, train, raws, train_ds, snap = base
        ctl = RefitController(model)
        ctl.prime(train_ds)
        window = rows_to_dataset(make_records(N_TRAIN, 21, shift=2.0), raws)
        with measure_compiles() as probe:
            res = ctl.refit(window)
        assert res.backend_compiles == 0, res
        assert probe.backend_compiles == 0
        assert res.prefix_reused is True
        assert res.diagnostics == []  # no TM809
        # the candidate is a genuinely retrained model over frozen prep
        assert res.model is not model
        pred_name = next(f.name for f in model.result_features
                         if f.ftype.__name__ == "Prediction")
        out = res.model.serving_plan(strict=True).score(
            [dict(make_records(4, 22)[0])])
        assert pred_name in out[0]

    def test_scripted_refit_fault_retries_then_succeeds(self, base):
        model, train, raws, train_ds, snap = base
        ctl = RefitController(model, sleep=lambda s: None)
        harness = FaultHarness(seed=0)
        harness.script("refit", [TransientScoringError("injected"), None])
        with harness:
            res = ctl.refit(train_ds)
        assert res.attempts == 2
        assert harness.calls["refit"] == 2

    def test_exhausted_retries_raise_refit_error_tm805(self, base):
        model, train, raws, train_ds, snap = base
        ctl = RefitController(model, max_retries=1, sleep=lambda s: None)
        harness = FaultHarness(seed=0)
        harness.fail_when("refit", lambda ctx: True,
                          lambda: TransientScoringError("persistent"))
        with harness:
            with pytest.raises(RefitError) as ei:
                ctl.refit(train_ds)
        assert [d.code for d in ei.value.diagnostics] == ["TM805"]
        assert harness.calls["refit"] == 2  # bounded: initial + 1 retry
        # the base model is untouched and still scores
        model.serving_plan(strict=True).score([make_records(1, 23)[0]])

    def test_checkpoint_current_flips_only_on_promotion(self, base, tmp_path):
        """refit() saves the versioned candidate but CURRENT (the durable
        last-known-good) only flips via mark_current — i.e. after the swap
        commits; a gate-rejected candidate's save never becomes CURRENT."""
        model, train, raws, train_ds, snap = base
        d = str(tmp_path / "ckpt")
        ctl = RefitController(model, checkpoint_dir=d, sleep=lambda s: None)
        res1 = ctl.refit(train_ds)
        assert res1.checkpoint_path.endswith("model-0001")
        assert os.path.isdir(res1.checkpoint_path)
        # not promoted yet: no CURRENT pointer
        assert not os.path.exists(os.path.join(d, "CURRENT"))
        ctl.mark_current(res1.checkpoint_path)  # swap committed
        good = RefitController.load_checkpoint(d)
        rec = {k: v for k, v in make_records(1, 24)[0].items()
               if k != "label"}
        expect = res1.model.serving_plan().score([rec])
        assert good.serving_plan().score([rec]) == expect

        # a second refit whose candidate is REJECTED (never marked) leaves
        # CURRENT on the promoted version
        res2 = ctl.refit(train_ds)
        assert res2.checkpoint_path.endswith("model-0002")
        with open(os.path.join(d, "CURRENT")) as fh:
            assert fh.read().strip() == "model-0001"

        # a crashed version save (fault) also leaves CURRENT untouched
        harness = FaultHarness(seed=0)
        harness.fail_when("checkpoint", lambda ctx: True,
                          lambda: OSError("disk gone"))
        ctl2 = RefitController(model, checkpoint_dir=d, max_retries=0,
                               sleep=lambda s: None)
        with harness:
            with pytest.raises(RefitError):
                ctl2.refit(train_ds)
        with open(os.path.join(d, "CURRENT")) as fh:
            assert fh.read().strip() == "model-0001"
        assert RefitController.load_checkpoint(d) is not None

    def test_scripted_checkpoint_fault_retries(self, base, tmp_path):
        model, train, raws, train_ds, snap = base
        d = str(tmp_path / "ckpt2")
        ctl = RefitController(model, checkpoint_dir=d, sleep=lambda s: None)
        harness = FaultHarness(seed=0)
        harness.script("checkpoint", [OSError("transient disk")])
        with harness:
            res = ctl.refit(train_ds)
        assert res.attempts == 2
        assert os.path.isdir(res.checkpoint_path)  # retried save landed


# ---------------------------------------------------------------------------
# Shadow scoring + atomic swap
# ---------------------------------------------------------------------------

class TestPredictionDelta:
    def test_nested_prediction_dicts_compare_shared_keys(self):
        a = {"p": {"prediction": 1.0, "probability_1": 0.8}, "label": 1.0}
        b = {"p": {"prediction": 0.0, "probability_1": 0.55}}
        assert prediction_delta(a, b) == 1.0

    def test_nan_delta_is_infinite(self):
        assert math.isinf(prediction_delta({"v": float("nan")}, {"v": 1.0}))

    def test_nothing_comparable_is_none(self):
        assert prediction_delta({"v": "text"}, {"v": "other"}) is None
        assert prediction_delta({"v": True}, {"v": False}) is None


class TestSwapAndShadow:
    def _server(self, model, **kw):
        kw.setdefault("max_batch", 32)
        kw.setdefault("max_wait_ms", 1.0)
        kw.setdefault("max_queue", 4096)
        return ScoringServer(model, **kw)

    def _candidate(self, base):
        model, train, raws, train_ds, snap = base
        ctl = RefitController(model)
        ctl.prime(train_ds)
        return ctl.refit(rows_to_dataset(
            make_records(N_TRAIN, 31, shift=2.0), raws)).model

    def test_schema_changing_candidate_refused_tm507(self, base):
        import pandas as pd

        model, train, *_ = base
        label = FeatureBuilder.RealNN("label").extract_field().as_response()
        other = FeatureBuilder.Real("num0").extract_field().as_predictor()
        vec = transmogrify([other])
        sel = BinaryClassificationModelSelector.with_train_validation_split(
            models=[(LogisticRegression(), [{"reg_param": 0.1}])])
        pred2 = label.transform_with(sel, label.sanity_check(vec))
        other_model = (Workflow().set_result_features(label, pred2)
                       .set_reader(DataReaders.Simple.dataframe(
                           pd.DataFrame(train)))).train()
        with self._server(model) as server:
            with measure_compiles() as probe:
                with pytest.raises(OpCheckError) as ei:
                    server.stage_candidate(other_model)
            assert any(d.code == "TM507" for d in ei.value.report)
            assert not server.has_candidate()
            # refused BEFORE any bucket executable compiled for it
            assert probe.backend_compiles == 0

    def test_shadow_mirrors_and_promotes_shared_prefix(self, base):
        """Mirrored traffic accumulates delta stats without touching primary
        futures; a frozen-prep candidate swaps with SHARED prefix
        executables (equal plan fingerprints) at zero new compiles."""
        model, train, raws, train_ds, snap = base
        cand = self._candidate(base)
        records = [{k: v for k, v in r.items() if k != "label"}
                   for r in make_records(96, 32)]
        with self._server(model) as server:
            before_fp = server.plan.fingerprint
            with measure_compiles() as probe:
                server.stage_candidate(cand)
            assert probe.backend_compiles == 0  # shared executable cache
            baseline = [f.result(5) for f in
                        [server.submit(r) for r in records]]
            rep = server.shadow_report()
            assert rep["mirrored_records"] == len(records)
            assert rep["shadow_failures"] == 0
            assert rep["compared_records"] == len(records)
            assert math.isfinite(rep["max_abs_delta"])
            swap = server.promote(probation_batches=2)
            assert swap["shared_prefix"] is True
            assert swap["from"] == before_fp == swap["to"]
            m = server.swap_metrics()
            assert m["swaps"] == 1 and m["active_version"] == 2
            # post-swap scoring serves the CANDIDATE model's host remainder
            after = [f.result(5) for f in
                     [server.submit(r) for r in records[:8]]]
            expect = cand.serving_plan(strict=False).score(records[:8])
            assert json.loads(json.dumps(after)) == \
                json.loads(json.dumps(expect))
            bm = server.metrics()["batcher"]
            assert bm["failed"] == 0 and bm["cancelled"] == 0
            assert bm["completed"] == bm["submitted"] == len(baseline) + 8

    def test_injected_swap_fault_leaves_active_serving(self, base):
        model, *_ = base
        cand = self._candidate(base)
        harness = FaultHarness(seed=0)
        harness.script("swap", [TransientScoringError("swap blip")])
        with self._server(model) as server:
            server.stage_candidate(cand)
            with harness:
                with pytest.raises(TransientScoringError):
                    server.promote()
                assert server.swap_metrics()["active_version"] == 1
                assert server.has_candidate()  # still staged, retryable
                swap = server.promote()  # schedule consumed: succeeds
            assert swap["to_version"] == 2
            assert server.swap_metrics()["swaps"] == 1

    def test_manual_rollback_restores_previous(self, base):
        model, *_ = base
        cand = self._candidate(base)
        with self._server(model) as server:
            server.stage_candidate(cand)
            server.promote(probation_batches=0)
            assert server.swap_metrics()["active_version"] == 2
            rec = server.rollback()
            assert rec["to_version"] == 1
            m = server.swap_metrics()
            assert m["active_version"] == 1 and m["rollbacks"] == 1

    def test_post_swap_breaker_trip_auto_rolls_back(self, base):
        """Acceptance: device faults after the swap open the promoted
        entry's breaker inside the probation window; the server rolls back
        to the retained last-known-good automatically, and every request
        still gets a result (host fallback, then the restored model)."""
        model, train, raws, train_ds, snap = base
        cand = self._candidate(base)
        records = [{k: v for k, v in r.items() if k != "label"}
                   for r in make_records(8, 33)]
        harness = FaultHarness(seed=0)
        with self._server(model, resilience={"max_retries": 0,
                                             "failure_threshold": 2,
                                             "recovery_batches": 8}) as srv:
            srv.stage_candidate(cand)
            srv.promote(probation_batches=6)
            assert srv.in_probation()
            harness.script("device", [TransientScoringError("dead"),
                                      TransientScoringError("dead")])
            with harness:
                out = []
                for r in records[:3]:  # one batch each (sequential submits)
                    out.append(srv.score(r, timeout=5))
            assert all("error" not in o for o in out)  # host path served
            m = srv.swap_metrics()
            assert m["rollbacks"] == 1
            assert m["active_version"] == 1  # last-known-good restored
            assert not srv.in_probation()
            hist = [h["event"] for h in m["history"]]
            assert hist == ["swap", "rollback"]
            # the restored model serves cleanly on its own breaker
            clean = srv.score(records[3], timeout=5)
            expect = model.serving_plan(strict=False).score([records[3]])[0]
            assert json.loads(json.dumps(clean)) == \
                json.loads(json.dumps(expect))


# ---------------------------------------------------------------------------
# The end-to-end control loop
# ---------------------------------------------------------------------------

class TestContinualE2E:
    def _run(self, base, records, harness=None, **kw):
        model, train, raws, train_ds, snap = base
        server = ScoringServer(model, max_batch=64, max_wait_ms=1.0,
                               max_queue=8192)
        refit = RefitController(model, sleep=lambda s: None,
                                **kw.pop("refit_kw", {}))
        trainer = ContinualTrainer(
            server, model, stream_reader(records), snapshot=snap,
            refit=refit, gate=PromotionGate(min_shadow_records=64),
            window_records=N_TRAIN, drift_params={"min_records": 128},
            probation_batches=2, **kw)
        try:
            if harness is not None:
                with harness:
                    metrics = trainer.run()
            else:
                metrics = trainer.run()
            server_metrics = server.metrics()
        finally:
            server.close()
        return trainer, metrics, server_metrics, server

    def test_drift_refit_shadow_swap_end_to_end(self, base):
        """The acceptance path: injected covariate drift -> detector fires
        -> zero-compile warm refit -> shadow parity gate -> atomic swap —
        with no dropped or double-scored in-flight requests."""
        model, train, raws, train_ds, snap = base
        records = make_records(512, 41, shift=3.0)
        trainer, metrics, sm, server = self._run(base, records)
        assert metrics["drift_events"] >= 1
        assert metrics["refits"] == 1
        assert metrics["promotions"] == 1
        assert metrics["gate_rejections"] == 0
        assert metrics["record_errors"] == 0
        # zero new backend compiles on the transform prefix (and the sweep)
        assert metrics["last_refit"]["backend_compiles"] == 0
        assert metrics["last_refit"]["prefix_reused"] is True
        # the swap shared the prefix executables and is now active
        swap = metrics["swap"]
        assert swap["swaps"] == 1 and swap["rollbacks"] == 0
        assert swap["active_version"] == 2
        assert swap["history"][0]["shared_prefix"] is True
        # no request dropped or double-scored through the whole stream
        bm = sm["batcher"]
        assert bm["submitted"] == len(records) == metrics["records"]
        assert bm["completed"] == bm["submitted"]
        assert bm["failed"] == 0 and bm["cancelled"] == 0
        assert bm["deadline_expired"] == 0
        codes = [d.code for d in trainer.diagnostics]
        assert "TM801" in codes and "TM807" in codes
        assert "TM806" not in codes and "TM809" not in codes

    def test_injected_refit_faults_keep_last_known_good(self, base):
        """Acceptance: with every refit attempt failing, the server keeps
        serving the last-known-good model and the stream completes."""
        model, *_ = base
        records = make_records(512, 42, shift=3.0)
        harness = FaultHarness(seed=0)
        harness.fail_when("refit", lambda ctx: True,
                          lambda: TransientScoringError("refit down"))
        trainer, metrics, sm, server = self._run(
            base, records, harness=harness, refit_kw={"max_retries": 1})
        assert metrics["refit_failures"] >= 1
        assert metrics["promotions"] == 0
        assert sm["swap"]["swaps"] == 0
        assert sm["swap"]["active_version"] == 1  # never swapped
        bm = sm["batcher"]
        assert bm["completed"] == bm["submitted"] == len(records)
        assert any(d.code == "TM805" for d in trainer.diagnostics)

    def test_bootstrap_mode_with_staged_candidate_does_not_crash(self, base):
        """Embedded use: a candidate staged through the public server API
        while the trainer is still bootstrapping its baseline (detector
        None) must not crash the loop on gate refusal/promotion paths."""
        model, train, raws, train_ds, snap = base
        cand = RefitController(model).refit(train_ds).model
        records = make_records(192, 47)
        server = ScoringServer(model, max_batch=64, max_wait_ms=1.0,
                               max_queue=8192)
        trainer = ContinualTrainer(
            server, model, stream_reader(records, batch=64),
            snapshot=None, bootstrap_records=10_000,  # never bootstraps
            gate=PromotionGate(min_shadow_records=64),
            probation_batches=2)
        try:
            server.stage_candidate(cand)
            metrics = trainer.run()  # must complete, not AttributeError
            assert metrics["records"] == len(records)
            # the staged candidate reached the gate and promoted cleanly
            assert server.swap_metrics()["swaps"] == 1
        finally:
            server.close()

    def test_injected_swap_fault_retries_then_promotes(self, base):
        model, *_ = base
        records = make_records(640, 43, shift=3.0)
        harness = FaultHarness(seed=0)
        harness.script("swap", [TransientScoringError("swap outage")])
        trainer, metrics, sm, server = self._run(base, records,
                                                 harness=harness)
        assert metrics["swap_failures"] == 1
        assert metrics["promotions"] == 1  # retried on the next batch
        assert sm["swap"]["active_version"] == 2

    def test_shadow_failures_refuse_promotion(self, base):
        """A candidate whose shadow scoring fails never swaps (TM806)."""
        model, *_ = base
        records = make_records(512, 44, shift=3.0)
        harness = FaultHarness(seed=0)
        harness.fail_when("shadow", lambda ctx: True,
                          lambda: TransientScoringError("shadow down"))
        trainer, metrics, sm, server = self._run(base, records,
                                                 harness=harness)
        assert metrics["refits"] >= 1
        assert metrics["promotions"] == 0
        assert metrics["gate_rejections"] >= 1
        assert sm["swap"]["active_version"] == 1
        assert any(d.code == "TM806" for d in trainer.diagnostics)

    def test_post_swap_trip_rolls_back_through_the_loop(self, base, tmp_path):
        """After the loop promotes, device faults inside the still-open
        probation window trip the breaker and restore the last-known-good
        model — and the trainer's rollback observer re-syncs its generation
        state: TM808 recorded, base model restored, CURRENT pointer
        reverted (cleared here: the pre-swap model was never checkpointed)."""
        model, train, raws, train_ds, snap = base
        records = make_records(512, 45, shift=3.0)
        ckpt_dir = str(tmp_path / "cks")
        server = ScoringServer(model, max_batch=64, max_wait_ms=1.0,
                               max_queue=8192,
                               resilience={"max_retries": 0,
                                           "failure_threshold": 2,
                                           "recovery_batches": 8})
        trainer = ContinualTrainer(
            server, model, stream_reader(records), snapshot=snap,
            refit=RefitController(model, sleep=lambda s: None,
                                  checkpoint_dir=ckpt_dir),
            gate=PromotionGate(min_shadow_records=64),
            window_records=N_TRAIN, drift_params={"min_records": 128},
            probation_batches=16)  # outlives the stream
        try:
            metrics = trainer.run()
            assert metrics["promotions"] == 1
            assert server.in_probation()
            # the promoted candidate's checkpoint became CURRENT
            with open(os.path.join(ckpt_dir, "CURRENT")) as fh:
                assert fh.read().strip() == "model-0001"
            promoted = trainer._model
            assert promoted is not model
            harness = FaultHarness(seed=0)
            harness.script("device", [TransientScoringError("dead"),
                                      TransientScoringError("dead")])
            probe = [{k: v for k, v in r.items() if k != "label"}
                     for r in make_records(4, 46, shift=3.0)]
            with harness:
                for r in probe[:3]:
                    server.score(r, timeout=5)  # host fallback, then trip
            m = server.swap_metrics()
            assert m["rollbacks"] == 1 and m["active_version"] == 1
            # the trainer observes the rollback on its next tick
            trainer._tick()
            assert any(d.code == "TM808" for d in trainer.diagnostics)
            assert trainer._model is model  # generation state restored
            # CURRENT no longer names the rolled-back candidate: the
            # pre-swap model was never checkpointed, so the pointer clears
            assert not os.path.exists(os.path.join(ckpt_dir, "CURRENT"))
            assert os.path.isdir(os.path.join(ckpt_dir, "model-0001"))
        finally:
            server.close()


# ---------------------------------------------------------------------------
# cli serve --follow
# ---------------------------------------------------------------------------

class TestCliFollow:
    def test_follow_refit_end_to_end(self, base, tmp_path):
        """`cli serve --follow --refit` drives MicroBatchStreamingReader end
        to end: tailed JSONL in, scored JSONL out, offsets committed, drift
        -> refit -> promotion recorded, checkpoint CURRENT written."""
        model, train, raws, train_ds, snap = base
        model_dir = str(tmp_path / "model")
        model.save(model_dir)
        baseline = str(tmp_path / "baseline.json")
        snap.save(baseline)
        # two drift segments (+3 then -3): the post-promotion rebase must
        # re-arm the detector AND the rebased RefitController must keep its
        # checkpoint_dir across generations
        records = make_records(512, 51, shift=3.0) \
            + make_records(512, 53, shift=-3.0)
        stream = tmp_path / "stream.jsonl"
        stream.write_text("".join(json.dumps(r) + "\n" for r in records))
        out_file = tmp_path / "scores.jsonl"
        metrics_file = tmp_path / "metrics.json"
        offsets = str(tmp_path / "offsets.json")
        ckpt_dir = str(tmp_path / "ckpts")

        from transmogrifai_tpu.cli.gen import main

        rc = main(["serve", "--model", model_dir,
                   "--records", str(stream),
                   "--output", str(out_file),
                   "--metrics-out", str(metrics_file),
                   "--follow", "--refit",
                   "--offsets", offsets,
                   "--baseline", baseline,
                   "--batch-interval", "0",
                   "--max-empty-polls", "1",
                   "--max-batch-records", "128",
                   "--drift-min-records", "128",
                   "--window-records", str(N_TRAIN),
                   "--shadow-records", "64",
                   "--probation-batches", "2",
                   "--checkpoint-dir", ckpt_dir,
                   "--max-wait-ms", "1"])
        assert rc == 0
        rows = [json.loads(line) for line in
                out_file.read_text().splitlines()]
        assert len(rows) == len(records)
        assert not any("error" in r for r in rows)
        metrics = json.loads(metrics_file.read_text())
        assert metrics["refits"] >= 2
        assert metrics["promotions"] >= 2
        assert metrics["last_refit"]["backend_compiles"] == 0
        assert metrics["server"]["swap"]["swaps"] >= 2
        # offsets committed through the end of the stream
        committed = json.load(open(offsets))
        assert committed["jsonl:stream.jsonl"] == stream.stat().st_size
        # EVERY generation saved a version (the rebased controller kept its
        # checkpoint_dir across promotions); CURRENT names a PROMOTED one
        with open(os.path.join(ckpt_dir, "CURRENT")) as fh:
            current = fh.read().strip()
        assert current.startswith("model-")
        assert int(current.split("-")[1]) <= metrics["refits"]
        assert os.path.isdir(os.path.join(ckpt_dir, "model-0001"))
        RefitController.load_checkpoint(ckpt_dir)

    def test_follow_without_refit_streams_and_commits(self, base, tmp_path):
        model, *_ = base
        model_dir = str(tmp_path / "m2")
        model.save(model_dir)
        records = make_records(64, 52)
        stream = tmp_path / "s2.jsonl"
        stream.write_text("".join(json.dumps(r) + "\n" for r in records))
        out_file = tmp_path / "o2.jsonl"
        offsets = str(tmp_path / "off2.json")

        from transmogrifai_tpu.cli.gen import main

        args = ["serve", "--model", model_dir, "--records", str(stream),
                "--output", str(out_file), "--metrics-out",
                str(tmp_path / "m2.json"), "--follow",
                "--offsets", offsets, "--batch-interval", "0",
                "--max-empty-polls", "1", "--max-wait-ms", "1"]
        rc = main(args)
        assert rc == 0
        assert len(out_file.read_text().splitlines()) == len(records)
        assert json.load(open(offsets))["jsonl:s2.jsonl"] \
            == stream.stat().st_size
        # resume regression: a second run with committed offsets scores
        # nothing new and must NOT truncate the already-written output
        rc2 = main(args)
        assert rc2 == 0
        assert len(out_file.read_text().splitlines()) == len(records)
