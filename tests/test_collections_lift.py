"""Map/list plumbing: FilterMap, collection lift, DateMapToUnitCircle (SURVEY §2.7)."""

import numpy as np

from transmogrifai_tpu.ops.collections_lift import (
    DateMapToUnitCircleVectorizer,
    FilterMap,
    LiftToList,
    LiftToMap,
)
from transmogrifai_tpu.ops.misc import ReplaceTransformer
from transmogrifai_tpu.testkit import (
    TestFeatureBuilder,
    assert_estimator_spec,
    assert_transformer_spec,
)
from transmogrifai_tpu.types import DateMap, Text, TextList, TextMap

MAPS = [
    {"a": "x", "b": "y", "c": ""},
    {"a": "z"},
    {},
    None,
]


class TestFilterMap:
    def test_white_list(self):
        f, ds = TestFeatureBuilder.of("m", TextMap, MAPS)
        stage = FilterMap(white_list_keys=("a",)).set_input(f)
        out = assert_transformer_spec(stage, ds)
        assert out.to_values()[0] == {"a": "x"}
        assert out.to_values()[1] == {"a": "z"}

    def test_black_list_and_empty_filter(self):
        f, ds = TestFeatureBuilder.of("m", TextMap, MAPS)
        stage = FilterMap(black_list_keys=("b",)).set_input(f)
        rows = stage.transform(ds)[stage.output_name].to_values()
        assert rows[0] == {"a": "x"}  # b black-listed, c empty-filtered

    def test_keep_empty_values(self):
        f, ds = TestFeatureBuilder.of("m", TextMap, MAPS)
        stage = FilterMap(filter_empty=False).set_input(f)
        rows = stage.transform(ds)[stage.output_name].to_values()
        assert rows[0] == {"a": "x", "b": "y", "c": ""}

    def test_output_type_matches_input(self):
        f, _ = TestFeatureBuilder.of("m", TextMap, MAPS)
        assert FilterMap().set_input(f).get_output().ftype is TextMap


class TestLift:
    def test_lift_to_map(self):
        f, ds = TestFeatureBuilder.of("m", TextMap, MAPS)
        inner = ReplaceTransformer(input_type=Text, old_value="x", new_value="XX")
        stage = LiftToMap(inner=inner).set_input(f)
        rows = stage.transform(ds)[stage.output_name].to_values()
        assert rows[0]["a"] == "XX"
        assert rows[0]["b"] == "y"
        assert rows[2] == {}

    def test_lift_to_list(self):
        f, ds = TestFeatureBuilder.of("l", TextList, [["x", "y"], [], None])
        inner = ReplaceTransformer(input_type=Text, old_value="y", new_value="Z")
        stage = LiftToList(inner=inner).set_input(f)
        rows = stage.transform(ds)[stage.output_name].to_values()
        assert rows[0] == ["x", "Z"]
        assert rows[1] == []

    def test_lift_serde_round_trip(self):
        from transmogrifai_tpu.testkit.specs import _roundtrip

        f, ds = TestFeatureBuilder.of("m", TextMap, MAPS)
        inner = ReplaceTransformer(input_type=Text, old_value="x", new_value="XX")
        stage = LiftToMap(inner=inner).set_input(f)
        expected = stage.transform(ds)[stage.output_name].to_values()
        restored = _roundtrip(stage)
        assert restored.transform(ds)[restored.output_name].to_values() == expected


HOUR_MS = 3_600_000


class TestDateMapToUnitCircle:
    def test_fit_learns_keys_and_encodes(self):
        maps = [
            {"signup": 0, "last": 6 * HOUR_MS},     # hour 0 and hour 6
            {"signup": 12 * HOUR_MS},
            None,
        ]
        f, ds = TestFeatureBuilder.of("d", DateMap, maps)
        est = DateMapToUnitCircleVectorizer(time_periods=("HourOfDay",)).set_input(f)
        model = assert_estimator_spec(est, ds, check_row_parity=False)
        assert model.key_sets == [["last", "signup"]]
        block = np.asarray(model.transform(ds)[model.output_name].data)
        assert block.shape == (3, 4)  # 2 keys x 1 period x (cos, sin)
        # signup hour 0 -> (1, 0); hour 12 -> (-1, 0); missing -> origin
        np.testing.assert_allclose(block[0, 2:], [1.0, 0.0], atol=1e-6)
        np.testing.assert_allclose(block[1, 2:], [-1.0, 0.0], atol=1e-6)
        np.testing.assert_allclose(block[2], 0.0)
        # last @ hour 6 -> (0, 1)
        np.testing.assert_allclose(block[0, :2], [0.0, 1.0], atol=1e-6)

    def test_metadata_grouping_per_key(self):
        f, ds = TestFeatureBuilder.of("d", DateMap, [{"k1": 0, "k2": 0}])
        model = DateMapToUnitCircleVectorizer(
            time_periods=("HourOfDay",)).set_input(f).fit(ds)
        out = model.transform(ds)[model.output_name]
        groups = [c.grouping for c in out.meta.columns]
        assert groups == ["d_k1", "d_k1", "d_k2", "d_k2"]

    def test_unknown_period_rejected(self):
        import pytest

        f, ds = TestFeatureBuilder.of("d", DateMap, [{"k": 0}])
        model = DateMapToUnitCircleVectorizer(
            time_periods=("NotAPeriod",)).set_input(f).fit(ds)
        with pytest.raises(ValueError, match="NotAPeriod"):
            model.transform(ds)
