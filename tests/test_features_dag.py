"""Feature DAG, builder, stage wiring, and scheduler tests (SURVEY §2.2, §2.3)."""

import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.data.dataset import Column
from transmogrifai_tpu.features.generator import FeatureGeneratorStage
from transmogrifai_tpu.stages.base import (
    BinaryTransformer,
    Estimator,
    Param,
    Transformer,
    UnaryTransformer,
)
from transmogrifai_tpu.types import Real, RealNN, Text
from transmogrifai_tpu.workflow.dag import compute_dag, raw_feature_generators


class AddTwo(BinaryTransformer):
    input_types = (Real, Real)
    output_type = Real

    def transform_columns(self, cols, dataset):
        a, b = cols[0].values_f64(), cols[1].values_f64()
        out = a + b
        return Column.from_values(Real, [None if np.isnan(v) else v for v in out])


class Scale(UnaryTransformer):
    input_types = (Real,)
    output_type = Real
    factor = Param(default=2.0, doc="multiplier")

    def transform_columns(self, cols, dataset):
        v = cols[0].values_f64() * self.factor
        return Column.from_values(Real, [None if np.isnan(x) else x for x in v])


def _raw(name, ftype=Real, response=False):
    b = FeatureBuilder.of(name, ftype).extract_field()
    return b.as_response() if response else b.as_predictor()


class TestFeature:
    def test_builder_creates_raw_feature(self):
        f = _raw("age")
        assert f.is_raw and f.name == "age" and f.ftype is Real
        assert not f.is_response
        assert isinstance(f.origin_stage, FeatureGeneratorStage)

    def test_response_flag(self):
        assert _raw("y", RealNN, response=True).is_response

    def test_builder_dynamic_type_attr(self):
        f = FeatureBuilder.Text("desc").as_predictor()
        assert f.ftype is Text

    def test_transform_with_wires_dag(self):
        a, b = _raw("a"), _raw("b")
        s = AddTwo()
        out = a.transform_with(s, b)
        assert out.parents == (a, b)
        assert out.origin_stage is s
        assert not out.is_raw
        assert out.ftype is Real

    def test_raw_features_dedup(self):
        a, b = _raw("a"), _raw("b")
        s1 = a.transform_with(AddTwo(), b)
        s2 = s1.transform_with(AddTwo(), a)  # a used twice
        raws = s2.raw_features()
        assert {f.name for f in raws} == {"a", "b"}
        assert len(raws) == 2

    def test_history(self):
        a, b = _raw("a"), _raw("b")
        out = a.transform_with(AddTwo(), b).transform_with(Scale())
        h = out.history()
        assert h.origin_features == ["a", "b"]
        assert "addTwo" in h.stages and "scale" in h.stages


class TestStageFramework:
    def test_arity_validation(self):
        a = _raw("a")
        with pytest.raises(ValueError):
            AddTwo().set_input(a)  # needs 2 inputs

    def test_type_validation(self):
        t = FeatureBuilder.Text("t").as_predictor()
        a = _raw("a")
        with pytest.raises(TypeError):
            AddTwo().set_input(a, t)

    def test_response_inputs_rejected_by_default(self):
        y = _raw("y", RealNN, response=True)
        a = _raw("a")
        with pytest.raises(ValueError):
            AddTwo().set_input(a, y)

    def test_params(self):
        s = Scale(factor=3.0)
        assert s.factor == 3.0
        assert s.get_params() == {"factor": 3.0}
        s.set_params(factor=5.0)
        assert s.factor == 5.0
        with pytest.raises(TypeError):
            Scale(bogus=1)

    def test_uid_unique(self):
        assert Scale().uid != Scale().uid

    def test_copy_preserves_identity(self):
        a = _raw("a")
        s = Scale(factor=4.0)
        out = a.transform_with(s)
        c = s.copy()
        assert c.uid == s.uid and c.factor == 4.0
        assert c.get_output() is out

    def test_transform_on_dataset(self):
        a, b = _raw("a"), _raw("b")
        s = AddTwo()
        out = a.transform_with(s, b)
        ds = Dataset.from_features(
            {"a": [1.0, None, 3.0], "b": [10.0, 20.0, 30.0]},
            {"a": Real, "b": Real},
        )
        ds2 = s.transform(ds)
        assert ds2[out.name].to_values() == [11.0, None, 33.0]


class TestDagScheduler:
    def test_layers_by_distance(self):
        a, b, c = _raw("a"), _raw("b"), _raw("c")
        s1, s2, s3 = AddTwo(), AddTwo(), AddTwo()
        ab = a.transform_with(s1, b)        # depth 2 from sink
        abc = ab.transform_with(s2, c)      # depth 1
        scale = Scale()
        final = abc.transform_with(scale)   # depth 0
        layers = compute_dag([final])
        assert [len(l) for l in layers] == [1, 1, 1]
        assert layers[0] == [s1] and layers[1] == [s2] and layers[2] == [scale]

    def test_diamond_max_distance(self):
        # a -> s1 -> x ; (x, x) -> s2 ; s1 must land in the layer at max distance
        a, b = _raw("a"), _raw("b")
        s1 = AddTwo()
        x = a.transform_with(s1, b)
        s2 = Scale()
        y = x.transform_with(s2)
        s3 = AddTwo()
        z = x.transform_with(s3, y)  # x used at distance 1 and 2
        layers = compute_dag([z])
        flat = [s for l in layers for s in l]
        assert flat.index(s1) < flat.index(s2) < flat.index(s3)

    def test_multiple_results_shared_stages(self):
        a, b = _raw("a"), _raw("b")
        s1 = AddTwo()
        x = a.transform_with(s1, b)
        s2, s3 = Scale(), Scale(factor=3.0)
        r1, r2 = x.transform_with(s2), x.transform_with(s3)
        layers = compute_dag([r1, r2])
        assert layers[0] == [s1]
        assert set(layers[1]) == {s2, s3}

    def test_raw_generators(self):
        a, b = _raw("a"), _raw("b")
        out = a.transform_with(AddTwo(), b)
        gens = raw_feature_generators([out])
        assert [g.raw_name for g in gens] == ["a", "b"]


class TestDataset:
    def test_from_features_and_masks(self):
        ds = Dataset.from_features(
            {"a": [1.0, None], "t": ["x", None]}, {"a": Real, "t": Text}
        )
        assert ds.n_rows == 2
        assert ds["a"].fill_rate() == 0.5
        assert list(ds["a"].present()) == [True, False]
        assert ds["t"].to_values() == ["x", None]

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            Dataset({
                "a": Column.from_values(Real, [1.0]),
                "b": Column.from_values(Real, [1.0, 2.0]),
            })

    def test_take_split_concat(self):
        ds = Dataset.from_features({"a": list(map(float, range(100)))}, {"a": Real})
        tr, te = ds.split(test_fraction=0.2, seed=1)
        assert tr.n_rows == 80 and te.n_rows == 20
        assert tr.concat(te).n_rows == 100

    def test_vector_column(self):
        col = Column.vector(np.arange(6, dtype=np.float32).reshape(3, 2))
        assert col.width == 2 and len(col) == 3

    def test_from_dataframe_inference(self):
        import pandas as pd

        df = pd.DataFrame({
            "age": [1.0, 2.0, None],
            "n": [1, 2, 3],
            "name": ["a", "b", None],
            "y": [0.0, 1.0, 0.0],
        })
        feats, ds = FeatureBuilder.from_dataframe(df, response="y")
        byname = {f.name: f for f in feats}
        assert byname["age"].ftype is Real
        assert byname["n"].ftype.__name__ == "Integral"
        assert byname["name"].ftype is Text
        assert byname["y"].is_response and byname["y"].ftype is RealNN
        assert ds.n_rows == 3
