"""Unified telemetry (ISSUE 11): trace spans, metrics registry, and the
flight-recorder event log across train/serve/refit.

Acceptance criteria proven here:
- under an injected fault schedule (breaker trip -> auto-rollback), the
  flight-recorder dump contains the compile events, breaker transition,
  swap, and rollback events in causal order with matching plan
  fingerprints (TestFlightE2E);
- a warm refit run records ZERO compile events, and the TM901
  unexpected-recompile diagnostic fires when one is injected;
- the Chrome-trace export of a ``cli serve`` replay is structurally valid
  (non-negative ts/dur, pid/tid present, X events) and spans nest
  correctly within every batcher worker thread (TestCliTelemetry);
- telemetry is default-off and every exported metrics/flight payload is
  ``json.dumps``-able with stable key ordering (satellite round-trip).
"""

import json
import os
import time

import numpy as np
import pytest

from transmogrifai_tpu import (
    BinaryClassificationModelSelector,
    FeatureBuilder,
    Workflow,
    transmogrify,
)
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.obs import (
    CANONICAL_METRICS,
    FlightRecorder,
    MetricsRegistry,
    Telemetry,
    Tracer,
    flight as obs_flight,
    resolve_telemetry,
    trace as obs_trace,
)
from transmogrifai_tpu.obs.metrics import assert_json_stable, legacy_aliases
from transmogrifai_tpu.readers.files import DataReaders
from transmogrifai_tpu.serve import (
    FaultHarness,
    ScoringServer,
    TransientScoringError,
)
from transmogrifai_tpu.workflow.continual import RefitController
from transmogrifai_tpu.workflow.workflow import dedup_raw_features

N_TRAIN = 256


def make_records(n, seed, shift=0.0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 3)) + shift
    out = []
    for i in range(n):
        rec = {"label": float(r.random() < 1 / (1 + np.exp(-x[i, 0])))}
        for j in range(3):
            rec[f"num{j}"] = float(x[i, j])
        out.append(rec)
    return out


@pytest.fixture(scope="module")
def base():
    """(model, train records, raw features, train dataset, candidate) —
    candidate is a frozen-prep warm-refit model sharing the plan
    fingerprint (the swap e2e needs matching fingerprints)."""
    import pandas as pd

    from transmogrifai_tpu.readers.base import rows_to_dataset

    train = make_records(N_TRAIN, 1)
    label = FeatureBuilder.RealNN("label").extract_field().as_response()
    feats = [FeatureBuilder.Real(f"num{j}").extract_field().as_predictor()
             for j in range(3)]
    checked = label.sanity_check(transmogrify(feats))
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        models=[(LogisticRegression(), [{"reg_param": 0.01}])])
    pred = label.transform_with(sel, checked)
    model = (Workflow().set_result_features(label, pred)
             .set_reader(DataReaders.Simple.dataframe(pd.DataFrame(train)))
             ).train()
    raws = dedup_raw_features(model.result_features)
    train_ds = rows_to_dataset(train, raws)
    refit = RefitController(model, sleep=lambda s: None)
    refit.prime(train_ds)
    candidate = refit.refit(train_ds).model
    return model, train, raws, train_ds, candidate


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts and ends with telemetry fully uninstalled."""
    obs_trace.uninstall_tracer()
    obs_flight.uninstall_recorder()
    yield
    obs_trace.uninstall_tracer()
    obs_flight.uninstall_recorder()


def nesting_violations(events):
    """Within each tid, X events must be properly nested: any two spans
    either disjoint or one contains the other (small float tolerance)."""
    by_tid = {}
    for ev in events:
        if ev.get("ph") == "X":
            by_tid.setdefault(ev["tid"], []).append(ev)
    bad = []
    eps = 1.0  # us: timestamps round to 0.1us; clock noise tolerance
    for tid, evs in by_tid.items():
        evs = sorted(evs, key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for ev in evs:
            while stack and ev["ts"] >= stack[-1]["ts"] \
                    + stack[-1]["dur"] - eps:
                stack.pop()
            if stack and ev["ts"] + ev["dur"] > stack[-1]["ts"] \
                    + stack[-1]["dur"] + eps:
                bad.append((tid, stack[-1]["name"], ev["name"]))
            stack.append(ev)
    return bad


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------

class TestTracer:
    def test_disabled_is_noop(self):
        assert obs_trace.active_tracer() is None
        with obs_trace.span("nothing", cat="test"):
            pass
        obs_trace.instant("nothing")  # must not raise anywhere

    def test_span_nesting_records_contextvar_parent(self):
        tracer = obs_trace.install_tracer(Tracer())
        try:
            with obs_trace.span("outer", cat="test"):
                with obs_trace.span("inner", cat="test"):
                    assert obs_trace.current_span_stack() == ("outer",
                                                              "inner")
        finally:
            obs_trace.uninstall_tracer()
        evs = tracer.chrome_trace()["traceEvents"]
        inner = next(e for e in evs if e.get("name") == "inner")
        outer = next(e for e in evs if e.get("name") == "outer")
        assert inner["args"]["parent"] == "outer"
        assert "parent" not in outer["args"]
        # inner nests inside outer on the same thread
        assert nesting_violations(evs) == []

    def test_chrome_trace_structure(self):
        tracer = obs_trace.install_tracer(Tracer())
        try:
            with obs_trace.span("a", cat="test", k=1):
                time.sleep(0.001)
            obs_trace.instant("mark", cat="test")
        finally:
            obs_trace.uninstall_tracer()
        doc = tracer.chrome_trace()
        assert "traceEvents" in doc
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        metas = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
        insts = [e for e in doc["traceEvents"] if e.get("ph") == "i"]
        assert len(xs) == 1 and len(insts) == 1
        assert any(m["name"] == "thread_name" for m in metas)
        for e in xs + insts:
            assert e["ts"] >= 0 and "pid" in e and "tid" in e
        assert xs[0]["dur"] >= 1000  # slept 1ms
        json.dumps(doc)  # exportable

    def test_bounded_capacity_counts_drops(self):
        tracer = Tracer(capacity=4)
        for i in range(10):
            tracer.add_instant(f"e{i}", "test")
        assert len(tracer) == 4 and tracer.dropped == 6

    def test_second_install_raises(self):
        t = obs_trace.install_tracer(Tracer())
        try:
            with pytest.raises(RuntimeError):
                obs_trace.install_tracer(Tracer())
        finally:
            obs_trace.uninstall_tracer(t)

    def test_requests_detail_validation(self):
        with pytest.raises(ValueError):
            Tracer(detail="everything")


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

class TestMetricsRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("tmog_test_total")
        c.inc()
        c.inc(3)
        assert c.value == 4
        g = reg.gauge("tmog_test_depth")
        g.set(7)
        assert g.value == 7
        h = reg.histogram("tmog_test_size", exact=True)
        for v in (1, 2, 2, 8):
            h.observe(v)
        assert h.count == 4 and h.sum == 13
        assert h.exact_counts() == {1: 1, 2: 2, 8: 1}
        assert h.quantile(0.5) == 2

    def test_get_or_create_and_type_conflict(self):
        reg = MetricsRegistry()
        a = reg.counter("tmog_x_total")
        assert reg.counter("tmog_x_total") is a
        with pytest.raises(TypeError):
            reg.gauge("tmog_x_total")

    def test_labels_render_in_prometheus(self):
        reg = MetricsRegistry()
        reg.counter("tmog_y_total", "help text",
                    labels={"entry": "1"}).inc(5)
        reg.counter("tmog_y_total", labels={"entry": "2"}).inc(7)
        text = reg.to_prometheus()
        assert '# TYPE tmog_y_total counter' in text
        assert 'tmog_y_total{entry="1"} 5' in text
        assert 'tmog_y_total{entry="2"} 7' in text
        assert '# HELP tmog_y_total help text' in text

    def test_snapshot_sorted_and_json_stable(self):
        reg = MetricsRegistry()
        reg.counter("tmog_b_total").inc()
        reg.counter("tmog_a_total").inc()
        reg.histogram("tmog_c_size", exact=True).observe(3)
        snap = reg.snapshot()
        assert list(snap) == sorted(snap)
        assert assert_json_stable(snap)  # dumps with sort_keys

    def test_canonical_table_audit(self):
        """Satellite: the canonical name table is collision-free — every
        (owner, legacy alias) pair maps to exactly ONE canonical name, and
        the styles that collided across the old namespaces (e.g. the
        batcher's 'cancelled' vs the swap layer's 'shadow_dropped') are
        disambiguated by the owner prefix in the canonical name."""
        seen = {}
        for name, (kind, owner, alias, help_) in CANONICAL_METRICS.items():
            assert kind in ("counter", "gauge", "histogram"), name
            assert name.startswith("tmog_"), name
            assert help_, f"{name} has no help text"
            if alias is not None:
                key = (owner, alias)
                assert key not in seen, \
                    f"alias collision: {key} -> {seen.get(key)} and {name}"
                seen[key] = name
        # the historic cross-namespace collisions are now distinct names
        assert ("batcher", "batches") in seen \
            and ("continual", "batches") in seen
        assert seen[("batcher", "batches")] != seen[("continual", "batches")]


class TestRegistryEviction:
    def test_drop_labeled_and_labeled_values(self):
        reg = MetricsRegistry()
        reg.counter("tmog_z_total", labels={"entry": "1"}).inc()
        reg.counter("tmog_z_total", labels={"entry": "2"}).inc()
        reg.gauge("tmog_z_state", labels={"entry": "1"}).set(1)
        assert reg.labeled_values("entry") == ["1", "2"]
        assert reg.drop_labeled("entry", "1") == 2
        assert reg.labeled_values("entry") == ["2"]
        assert 'tmog_z_total{entry="2"}' in reg.snapshot()

    def test_server_prunes_dead_entry_series(self, base):
        """A continual loop stages one entry per refit; the registry must
        stay bounded to the live active/previous/candidate generations."""
        model, train, raws, train_ds, candidate = base
        with ScoringServer(model, max_batch=8, max_wait_ms=1.0) as server:
            for _ in range(4):  # stage/discard churn: versions 2..5
                server.stage_candidate(candidate, warm=False)
                server.discard_candidate()
            server.stage_candidate(candidate, warm=False)
            live = set(server.registry.labeled_values("entry"))
            # active v1 + the latest candidate only — dead entries evicted
            assert "1" in live and len(live) <= 3, live


class TestTelemetryOwnership:
    def test_nested_enter_does_not_tear_down_outer(self, tmp_path):
        tel = Telemetry(out_dir=str(tmp_path / "t"))
        with tel:
            with tel:  # inner enter: not the owner
                pass
            # outer session still recording
            assert obs_trace.active_tracer() is tel.tracer
            assert obs_flight.active_recorder() is tel.recorder
        assert obs_trace.active_tracer() is None

    def test_train_with_caller_started_telemetry(self, base, tmp_path):
        """train(telemetry=<already-started bundle>) must not stop the
        caller's session (and must not dump over it mid-session)."""
        import pandas as pd

        model, train, *_ = base
        label = FeatureBuilder.RealNN("label").extract_field().as_response()
        feats = [FeatureBuilder.Real(f"num{j}").extract_field()
                 .as_predictor() for j in range(3)]
        checked = label.sanity_check(transmogrify(feats))
        sel = BinaryClassificationModelSelector.with_train_validation_split(
            models=[(LogisticRegression(), [{"reg_param": 0.01}])])
        pred = label.transform_with(sel, checked)
        tel = Telemetry(out_dir=str(tmp_path / "outer")).start()
        try:
            (Workflow().set_result_features(label, pred)
             .set_reader(DataReaders.Simple.dataframe(pd.DataFrame(train)))
             ).train(telemetry=tel)
            # the caller's session survived the inner train
            assert obs_trace.active_tracer() is tel.tracer
            assert not os.path.exists(tmp_path / "outer" / "trace.json")
        finally:
            tel.stop()


class TestPartialWarm:
    def test_partial_warm_does_not_arm_tm901(self, base):
        model, *_ = base
        plan = model.serving_plan(strict=False)
        plan.warm(buckets=[8])  # partial: later buckets legitimately compile
        assert plan._warmed is False
        plan.warm()  # the full ladder arms the expectation
        assert plan._warmed is True


class TestLegacyViews:
    """Satellite: metrics() plain dicts survive as views over the registry,
    and every exported payload round-trips through json with stable keys."""

    def test_batcher_view_matches_registry(self):
        from transmogrifai_tpu.serve import MicroBatcher

        with MicroBatcher(lambda recs: [{"v": 1} for _ in recs],
                          max_batch=4, max_wait_ms=1.0) as mb:
            for _ in range(3):
                mb.score({"a": 1})
            view = mb.metrics()
            snap = mb.registry.snapshot()
        for legacy, canonical in legacy_aliases("batcher").items():
            assert legacy in view, legacy
            if legacy in ("batch_size_hist",):
                continue  # shape differs (exact counts vs summary)
            if isinstance(view[legacy], (int, float)):
                assert view[legacy] == snap[canonical], (legacy, canonical)
        assert view["submitted"] == 3 and view["completed"] == 3
        assert assert_json_stable(view)

    def test_server_views_json_stable(self, base):
        model, *_ = base
        with ScoringServer(model, max_batch=8, max_wait_ms=1.0) as server:
            server.score({f"num{j}": 0.1 for j in range(3)}, timeout=10)
            m = server.metrics()
            snap = server.metrics_snapshot()
            prom = server.prometheus()
        assert assert_json_stable(m)
        assert assert_json_stable(snap)
        # one registry covers batcher + swap + breaker + resilience
        assert "tmog_serve_batcher_submitted_total" in snap
        assert "tmog_serve_swap_swaps_total" in snap
        assert any(k.startswith("tmog_serve_breaker_state") for k in snap)
        assert any(k.startswith("tmog_serve_resilience_quarantined_total")
                   for k in snap)
        assert "# TYPE tmog_serve_batcher_submitted_total counter" in prom
        # legacy view values mirror the canonical source of truth
        assert m["batcher"]["submitted"] \
            == snap["tmog_serve_batcher_submitted_total"]

    def test_trainer_counters_view(self, base):
        from transmogrifai_tpu.readers import (ListSource,
                                               MicroBatchStreamingReader)
        from transmogrifai_tpu.workflow.continual import ContinualTrainer

        model, train, raws, train_ds, _cand = base
        reader = MicroBatchStreamingReader(
            ListSource(make_records(32, 5), "s"), batch_interval=0.0,
            max_batch_records=16, max_empty_polls=1)
        with ScoringServer(model, max_batch=16, max_wait_ms=1.0) as server:
            trainer = ContinualTrainer(server, model, reader,
                                       refit_enabled=False)
            metrics = trainer.run()
        assert trainer.counters["batches"] >= 2
        assert trainer.counters["records"] == 32
        # the trainer joined the SERVER's registry (one scrape covers both)
        assert server.registry.snapshot()["tmog_continual_records_total"] \
            == 32
        assert assert_json_stable(metrics)


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_bound_and_payload_stable(self):
        rec = FlightRecorder(capacity=4)
        for i in range(9):
            rec.record("tick", i=i)
        assert len(rec) == 4 and rec.dropped == 5
        payload = rec.to_payload()
        assert payload["events"][-1]["data"]["i"] == 8
        assert [e["seq"] for e in payload["events"]] == [6, 7, 8, 9]
        assert assert_json_stable(payload)

    def test_compile_event_tagged_with_context(self):
        import jax
        import jax.numpy as jnp

        rec = obs_flight.install_recorder(FlightRecorder())
        try:
            salt = time.time_ns() % 97

            @jax.jit
            def f(v):
                return (v * salt).sum()

            with obs_flight.compile_context("test.site",
                                            fingerprint="fp123"):
                f(jnp.arange(8, dtype=jnp.float32))
        finally:
            obs_flight.uninstall_recorder()
        evs = rec.events("backend_compile")
        assert len(evs) >= 1
        assert evs[-1]["data"]["site"] == "test.site"
        assert evs[-1]["data"]["fingerprint"] == "fp123"
        assert evs[-1]["data"]["unexpected"] is False
        assert rec.unexpected_compiles == 0

    def test_warm_context_compile_fires_tm901(self):
        import jax
        import jax.numpy as jnp

        rec = obs_flight.install_recorder(FlightRecorder())
        try:
            salt = time.time_ns() % 89

            @jax.jit
            def g(v):
                return (v + salt).sum() * 2

            # inner context inherits the WARM expectation from the outer
            # one (the refit wraps dispatch layers that open their own)
            with obs_flight.compile_context("outer.warm", warm=True):
                with obs_flight.compile_context("inner.dispatch",
                                                fingerprint="fpX"):
                    g(jnp.arange(16, dtype=jnp.float32))
        finally:
            obs_flight.uninstall_recorder()
        evs = rec.events("backend_compile")
        assert evs and evs[-1]["data"]["unexpected"] is True
        assert evs[-1]["data"]["site"] == "inner.dispatch"
        assert rec.unexpected_compiles >= 1
        diags = rec.diagnostics()
        assert diags and all(d.code == "TM901" for d in diags)
        assert "inner.dispatch" in diags[-1].message

    def test_fault_injection_records_and_autodumps(self, base, tmp_path):
        model, *_ = base
        rec = obs_flight.install_recorder(
            FlightRecorder(dump_dir=str(tmp_path)))
        harness = FaultHarness(seed=0)
        harness.script("device", [TransientScoringError("boom")])
        try:
            with ScoringServer(model, max_batch=4, max_wait_ms=1.0) as srv:
                with harness:
                    out = srv.score({f"num{j}": 0.2 for j in range(3)},
                                    timeout=10)
            assert "error" not in out  # retry/fallback served the record
        finally:
            obs_flight.uninstall_recorder()
        faults = rec.events("fault_injected")
        assert faults and faults[0]["data"]["point"] == "device"
        assert faults[0]["data"]["error"] == "TransientScoringError"
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight-fault-")]
        assert dumps, "injected fault did not auto-dump the recorder"
        blob = json.load(open(tmp_path / dumps[0]))
        assert blob["reason"] == "fault_injected:device"
        assert any(e["kind"] == "fault_injected" for e in blob["events"])


# ---------------------------------------------------------------------------
# Serve-path telemetry
# ---------------------------------------------------------------------------

class TestServeTelemetry:
    def test_spans_cover_the_request_lifecycle(self, base):
        model, *_ = base
        tel = Telemetry()
        with tel:
            with ScoringServer(model, max_batch=8, max_wait_ms=1.0) as srv:
                futs = [srv.submit({f"num{j}": 0.1 * i for j in range(3)})
                        for i in range(24)]
                for f in futs:
                    f.result(timeout=10)
        evs = tel.tracer.chrome_trace()["traceEvents"]
        names = {e["name"] for e in evs if e.get("ph") == "X"}
        assert {"serve.flush", "serve.encode", "serve.device",
                "serve.host"} <= names
        assert nesting_violations(evs) == []
        # pipelined serving (ISSUE 18): encode runs on the flusher thread
        # while the flush span wraps finalize on the finalizer thread, so
        # the causal chain joins on the batch_seq key, not the tid
        flush = next(e for e in evs if e["name"] == "serve.flush")
        seq = flush["args"].get("batch_seq")
        assert seq is not None
        enc = next(e for e in evs if e["name"] == "serve.encode"
                   and e["args"].get("batch_seq") == seq)
        host = next(e for e in evs if e["name"] == "serve.host"
                    and e["args"].get("batch_seq") == seq)
        # the host remainder runs inside its batch's finalize/flush span
        assert host["tid"] == flush["tid"]
        assert host["args"].get("parent") == "serve.flush"
        # encode precedes the batch's host remainder (overlap-safe order)
        assert enc["ts"] <= host["ts"]

    def test_warm_serve_records_zero_compile_events(self, base):
        """Acceptance: a WARM serve replay under the recorder logs no
        backend compiles — and an injected one raises TM901."""
        import jax
        import jax.numpy as jnp

        model, *_ = base
        with ScoringServer(model, max_batch=8, max_wait_ms=1.0) as srv:
            srv.score({f"num{j}": 0.3 for j in range(3)}, timeout=10)
            rec = obs_flight.install_recorder(FlightRecorder())
            try:
                for i in range(12):
                    srv.score({f"num{j}": 0.01 * i for j in range(3)},
                              timeout=10)
                assert rec.events("backend_compile") == []
                assert rec.unexpected_compiles == 0
                # inject a compile into the warm path: TM901 must fire
                salt = time.time_ns() % 83

                @jax.jit
                def h(v):
                    return (v - salt).sum()

                with obs_flight.compile_context("serve.warm-injected",
                                                warm=True):
                    h(jnp.arange(4, dtype=jnp.float32))
                # >= 1: one jit call may emit several backend programs
                assert rec.unexpected_compiles >= 1
                diags = rec.diagnostics()
                assert diags and {d.code for d in diags} == {"TM901"}
            finally:
                obs_flight.uninstall_recorder()


# ---------------------------------------------------------------------------
# The acceptance e2e: fault schedule -> flight record in causal order
# ---------------------------------------------------------------------------

class TestFlightE2E:
    def test_breaker_trip_rollback_causal_order(self, base):
        """Acceptance: under the injected fault schedule (breaker trip ->
        auto-rollback), the flight dump holds compile, breaker-transition,
        swap, and rollback events in causal (seq) order, with the swap's
        plan fingerprints matching the compile events'."""
        model, train, raws, train_ds, candidate = base
        rec = obs_flight.install_recorder(FlightRecorder())
        harness = FaultHarness(seed=0)
        records = [{k: v for k, v in r.items() if k != "label"}
                   for r in make_records(8, 33)]
        try:
            # min_bucket=2 keeps at least one bucket executable out of the
            # process-wide cache, so the build logs compile events even
            # after earlier tests served the same plan
            with ScoringServer(model, max_batch=4, max_wait_ms=1.0,
                               min_bucket=2,
                               resilience={"max_retries": 0,
                                           "failure_threshold": 2,
                                           "recovery_batches": 8}) as srv:
                srv.stage_candidate(candidate)
                srv.promote(probation_batches=6)
                harness.script("device", [TransientScoringError("dead"),
                                          TransientScoringError("dead")])
                with harness:
                    for r in records[:3]:
                        srv.score(r, timeout=10)
                m = srv.swap_metrics()
                assert m["rollbacks"] == 1 and m["active_version"] == 1
        finally:
            obs_flight.uninstall_recorder()

        payload = rec.to_payload()
        assert assert_json_stable(payload)
        compiles = rec.events("backend_compile")
        swaps = rec.events("swap")
        rollbacks = rec.events("rollback")
        transitions = rec.events("breaker_transition")
        faults = rec.events("fault_injected")
        assert compiles and swaps and rollbacks and transitions and faults
        # causal order: plan compiles < swap < injected faults < breaker
        # open < rollback
        opened = next(t for t in transitions if t["data"]["to"] == "open")
        assert max(c["seq"] for c in compiles) < swaps[0]["seq"]
        assert swaps[0]["seq"] < faults[0]["seq"] <= opened["seq"]
        assert opened["seq"] < rollbacks[0]["seq"]
        # matching plan fingerprints: the frozen-prep candidate shares the
        # active plan's fingerprint, and the compiles carry the same one
        fp = swaps[0]["data"]["from"]
        assert swaps[0]["data"]["to"] == fp  # shared prefix
        assert rollbacks[0]["data"]["from"] == fp
        assert rollbacks[0]["data"]["to"] == fp
        serve_compiles = [c for c in compiles
                          if c["data"]["site"] == "serve.plan"]
        assert serve_compiles
        assert all(c["data"]["fingerprint"] == fp for c in serve_compiles)
        assert all(c["data"]["unexpected"] is False for c in compiles)

    def test_warm_refit_zero_compile_events(self, base):
        """Acceptance: a warm refit under the recorder logs ZERO backend
        compiles (plan + executable caches hit) and no TM901."""
        model, train, raws, train_ds, _cand = base
        refit = RefitController(model, sleep=lambda s: None)
        refit.prime(train_ds)
        refit.refit(train_ds)  # ensure every program is cache-warm
        rec = obs_flight.install_recorder(FlightRecorder())
        try:
            res = refit.refit(train_ds)
        finally:
            obs_flight.uninstall_recorder()
        assert res.backend_compiles == 0
        assert rec.events("backend_compile") == []
        assert rec.unexpected_compiles == 0 and rec.diagnostics() == []


# ---------------------------------------------------------------------------
# CLI integration
# ---------------------------------------------------------------------------

class TestCliTelemetry:
    def _save(self, model, tmp_path):
        model_dir = str(tmp_path / "model")
        model.save(model_dir)
        return model_dir

    def test_cli_serve_telemetry_artifacts(self, base, tmp_path):
        """Acceptance: the Chrome-trace export of a ``cli serve`` replay is
        structurally valid and spans nest across batcher worker threads."""
        from transmogrifai_tpu.cli.gen import main

        model, *_ = base
        model_dir = self._save(model, tmp_path)
        records = [{k: v for k, v in r.items() if k != "label"}
                   for r in make_records(48, 7)]
        stream = tmp_path / "r.jsonl"
        stream.write_text("".join(json.dumps(r) + "\n" for r in records))
        teldir = tmp_path / "tel"
        # --min-bucket 1: bucket 1 is compiled by no other test, so the
        # flight record deterministically holds >=1 compile event even
        # after earlier tests warmed the process-wide executable cache
        rc = main(["serve", "--model", model_dir, "--records", str(stream),
                   "--output", str(tmp_path / "out.jsonl"),
                   "--metrics-out", str(tmp_path / "m.json"),
                   "--min-bucket", "1",
                   "--telemetry", str(teldir)])
        assert rc == 0
        assert sorted(os.listdir(teldir)) == [
            "flight.json", "metrics.jsonl", "metrics.prom", "trace.json"]
        doc = json.load(open(teldir / "trace.json"))
        evs = doc["traceEvents"]
        xs = [e for e in evs if e.get("ph") == "X"]
        assert xs, "no complete events in the trace"
        for e in xs:
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert {"serve.flush", "serve.encode", "serve.device",
                "serve.host"} <= {e["name"] for e in xs}
        # thread metadata present for the batcher worker thread
        names = {m["args"]["name"] for m in evs if m.get("ph") == "M"
                 and m["name"] == "thread_name"}
        assert any("microbatcher" in n for n in names), names
        # spans nest correctly within every thread
        assert nesting_violations(evs) == []
        # flight + prometheus artifacts parse
        fl = json.load(open(teldir / "flight.json"))
        assert any(e["kind"] == "backend_compile" for e in fl["events"])
        assert fl["unexpected_compiles"] == 0
        prom = (teldir / "metrics.prom").read_text()
        assert "tmog_serve_batcher_submitted_total" in prom
        line = json.loads(
            (teldir / "metrics.jsonl").read_text().splitlines()[-1])
        assert line["source"] == "cli serve"
        # scores are unaffected by telemetry
        rows = (tmp_path / "out.jsonl").read_text().splitlines()
        assert len(rows) == len(records)

    def test_follow_snapshot_lines(self, base, tmp_path):
        """Satellite: ``--follow --snapshot-interval`` emits periodic
        metrics-snapshot JSONL lines while scores and offsets stay
        byte-identical to a run without them."""
        from transmogrifai_tpu.cli.gen import main

        model, *_ = base
        model_dir = self._save(model, tmp_path)
        records = make_records(64, 9)
        stream = tmp_path / "s.jsonl"
        stream.write_text("".join(json.dumps(r) + "\n" for r in records))
        snaps = tmp_path / "snapshots.jsonl"
        offsets = str(tmp_path / "off.json")
        out_file = tmp_path / "o.jsonl"
        rc = main(["serve", "--model", model_dir, "--records", str(stream),
                   "--output", str(out_file),
                   "--metrics-out", str(tmp_path / "m.json"),
                   "--follow", "--offsets", offsets,
                   "--batch-interval", "0", "--max-empty-polls", "1",
                   "--max-batch-records", "16", "--max-wait-ms", "1",
                   "--snapshot-interval", "0",
                   "--snapshots-out", str(snaps)])
        assert rc == 0
        lines = [json.loads(ln) for ln in
                 snaps.read_text().splitlines()]
        assert len(lines) >= 4  # one per 16-record batch
        for ln in lines:
            assert ln["type"] == "metrics_snapshot"
            assert "tmog_serve_batcher_submitted_total" in ln["metrics"]
            assert "continual" in ln
        # scoring output and offsets unaffected
        assert len(out_file.read_text().splitlines()) == len(records)
        committed = json.load(open(offsets))
        assert committed["jsonl:s.jsonl"] == stream.stat().st_size
        metrics = json.loads((tmp_path / "m.json").read_text())
        assert metrics["metrics_snapshots_emitted"] == len(lines)

    def test_tmog_telemetry_env_switch(self, base, tmp_path, monkeypatch):
        """The TMOG_TELEMETRY env var enables the same artifacts with no
        CLI flag (and resolve_telemetry defers when already active)."""
        from transmogrifai_tpu.cli.gen import main

        model, *_ = base
        model_dir = self._save(model, tmp_path)
        records = [{k: v for k, v in r.items() if k != "label"}
                   for r in make_records(8, 11)]
        stream = tmp_path / "e.jsonl"
        stream.write_text("".join(json.dumps(r) + "\n" for r in records))
        teldir = tmp_path / "envtel"
        monkeypatch.setenv("TMOG_TELEMETRY", str(teldir))
        rc = main(["serve", "--model", model_dir, "--records", str(stream),
                   "--output", str(tmp_path / "eo.jsonl"),
                   "--metrics-out", str(tmp_path / "em.json")])
        assert rc == 0
        assert (teldir / "trace.json").exists()
        assert (teldir / "flight.json").exists()
        # while a bundle is active, env resolution returns None (an inner
        # train() must not fight the outer entry point)
        tel = Telemetry().start()
        try:
            assert resolve_telemetry(None) is None
        finally:
            tel.stop()


# ---------------------------------------------------------------------------
# Workflow.train telemetry + TMOG_PROFILE
# ---------------------------------------------------------------------------

class TestTrainTelemetry:
    def test_train_writes_trace_and_metrics(self, base, tmp_path):
        import pandas as pd

        model, train, *_ = base
        teldir = str(tmp_path / "traintel")
        label = FeatureBuilder.RealNN("label").extract_field().as_response()
        feats = [FeatureBuilder.Real(f"num{j}").extract_field()
                 .as_predictor() for j in range(3)]
        checked = label.sanity_check(transmogrify(feats))
        sel = BinaryClassificationModelSelector.with_train_validation_split(
            models=[(LogisticRegression(), [{"reg_param": 0.01}])])
        pred = label.transform_with(sel, checked)
        (Workflow().set_result_features(label, pred)
         .set_reader(DataReaders.Simple.dataframe(pd.DataFrame(train)))
         ).train(telemetry=teldir)
        doc = json.load(open(os.path.join(teldir, "trace.json")))
        cats = {e.get("cat") for e in doc["traceEvents"]
                if e.get("ph") == "X"}
        assert "train" in cats  # perf.phase sites re-emit as spans
        names = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        assert any(n.startswith("fit.") for n in names), names
        line = json.loads(open(os.path.join(teldir, "metrics.jsonl"))
                          .read().splitlines()[-1])
        assert line["source"] == "Workflow.train"
        assert "backend_compiles" in line["compile"]
        assert any(p.startswith("fit.") for p in line["phases"])
        assert os.path.exists(os.path.join(teldir, "flight.json"))
        # telemetry is OFF again after the context
        assert obs_trace.active_tracer() is None
        assert obs_flight.active_recorder() is None


class TestProfileHook:
    def test_profile_dir_created_and_scores_bitwise_identical(
            self, base, tmp_path, monkeypatch):
        """Satellite: TMOG_PROFILE wraps the serve dispatch in
        jax.profiler.trace — artifact dir created, scores unchanged."""
        model, *_ = base
        records = [{f"num{j}": 0.1 * i for j in range(3)}
                   for i in range(8)]
        plan = model.serving_plan(strict=False)
        baseline = plan.score(records)
        prof = tmp_path / "prof"
        monkeypatch.setenv("TMOG_PROFILE", str(prof))
        profiled = plan.score(records)
        monkeypatch.delenv("TMOG_PROFILE")
        assert os.path.isdir(prof)
        assert json.dumps(profiled, sort_keys=True) \
            == json.dumps(baseline, sort_keys=True)

    def test_unset_env_is_noop(self, base, monkeypatch):
        monkeypatch.delenv("TMOG_PROFILE", raising=False)
        from transmogrifai_tpu.obs.profile import maybe_profile, profile_dir

        assert profile_dir() == ""
        with maybe_profile("test"):
            pass
