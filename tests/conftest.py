"""Test configuration: force an 8-device virtual CPU mesh.

This plays the role of the reference's local[2] SparkSession (SURVEY §4): real sharding and
collective semantics on one host.  Must run before jax initializes its backends.
"""

import os
import sys

# Force CPU even when the ambient environment points JAX at a real TPU (axon):
# tests emulate a multi-chip mesh with 8 virtual CPU devices.
#
# NOTE: the environment may pre-import jax at interpreter start (axon sitecustomize),
# which snapshots JAX_PLATFORMS before this file runs — so setting os.environ is not
# enough; jax.config.update must be used after import.  XLA_FLAGS is still read at
# backend-init time, which has not happened yet here.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session", autouse=True)
def _compile_budget_report():
    """Print the suite-wide compile budget at session end: total backend
    compiles/seconds and the sweep executable-cache hit rate.  The sweep
    cache is process-wide, so test modules fitting same-bucket sweeps share
    warm executables — the hit counters make that visible per run."""
    yield
    try:
        from transmogrifai_tpu.perf import compile_snapshot, \
            program_cache_stats

        snap = compile_snapshot()
        prog = program_cache_stats()
        sys.stderr.write(
            f"\n[perf] suite compile budget: {snap.backend_compiles} backend "
            f"compiles, {snap.compile_seconds:.1f}s compiling; sweep "
            f"executable cache: {prog['programs_compiled']} compiled, "
            f"{prog['cache_hits']} hits, "
            f"{snap.persistent_cache_hits} persistent-cache hits\n")
    except Exception:
        pass
