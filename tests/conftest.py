"""Test configuration: force an 8-device virtual CPU mesh.

This plays the role of the reference's local[2] SparkSession (SURVEY §4): real sharding and
collective semantics on one host.  Must run before jax initializes its backends.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
