"""Hand-labeled Dutch real-prose NER fixture (VERDICT r4 #3).

105 sentences in news / fiction / correspondence / review register — NOT
generated from the training templates.  Labels are token ->
NameEntityType for every entity token (everything else is O), using
``ner_tokenize``'s tokenization.

Many names are real-world or invented entities absent from both the nl
gazetteers (ops/ner_lang.py) and the training fill lists; common ones
(Amsterdam, vrijdag) naturally overlap, as real Dutch text does.
"""

# (sentence, {token: entity_type})
REAL_TEXT_NL = [
    ("Toen de delegatie eindelijk Genève bereikte, waren de "
     "onderhandelingen al mislukt, en secretaris Terlouw weigerde "
     "commentaar.",
     {"Genève": "Location", "Terlouw": "Person"}),
    ("Het persbureau meldde donderdag dat Arcadis bijna 8% van zijn "
     "personeel zou schrappen voor december.",
     {"donderdag": "Date", "Arcadis": "Organization", "8%": "Percentage",
      "december": "Date"}),
    ("De oude vuurtorenwachter, een man genaamd Sible Terpstra, had het "
     "eiland sinds 1987 niet verlaten.",
     {"Sible": "Person", "Terpstra": "Person", "1987": "Date"}),
    ("Analisten van Rabobank verwachten dat de euro verzwakt tegenover "
     "de dollar voor de lente.",
     {"Rabobank": "Organization"}),
    ("Om 6:45 vertrok de veerboot uit Harlingen met post, kaas en één "
     "zeer nerveuze boekhouder.",
     {"6:45": "Time", "Harlingen": "Location"}),
    ("Hun dochter Margriet studeerde scheikunde in Wageningen voordat de "
     "oorlog uitbrak.",
     {"Margriet": "Person", "Wageningen": "Location"}),
    ("De schikking, goedgekeurd op 2019-03-22, verplichtte Koninklijke "
     "Volker tot €14M aan schadevergoeding.",
     {"2019-03-22": "Date", "Koninklijke": "Organization",
      "Volker": "Organization", "€14M": "Money"}),
    ("Niemand in het dorp Giethoorn herinnerde zich een koudere januari "
     "dan die.",
     {"Giethoorn": "Location", "januari": "Date"}),
    ("Professor Wiarda betoogde dat de cijfers van de Wereldbank de "
     "armoede met minstens 3.5% onderschatten.",
     {"Wiarda": "Person", "Wereldbank": "Organization",
      "3.5%": "Percentage"}),
    ("Het was bijna 11:30 toen inspecteur Vandecasteele aanklopte bij "
     "het pakhuis in Vlissingen.",
     {"11:30": "Time", "Vandecasteele": "Person",
      "Vlissingen": "Location"}),
    ("De omzet van Vopak steeg vorig kwartaal met 6%, zei het bedrijf "
     "maandag.",
     {"Vopak": "Organization", "6%": "Percentage", "maandag": "Date"}),
    ("In de zomer van 2003 openden twee broers uit Zaandam een bakkerij "
     "aan de Vijzelstraat.",
     {"2003": "Date", "Zaandam": "Location", "Vijzelstraat": "Location"}),
    ("De commissie hoorde de getuigenis van dr. Lindqvist, die de "
     "proeven in Leiden had geleid.",
     {"Lindqvist": "Person", "Leiden": "Location"}),
    ("De vrachtkosten stegen tot €2,400 per container nadat het kanaal "
     "in maart sloot.",
     {"€2,400": "Money", "maart": "Date"}),
    ("Mijn grootmoeder vertrok in 1952 uit Dokkum met twee koffers en "
     "een adres in Paramaribo.",
     {"1952": "Date", "Dokkum": "Location", "Paramaribo": "Location"}),
    ("Heineken en Grolsch kondigden vrijdag een gezamenlijke investering "
     "van €350M aan.",
     {"Heineken": "Organization", "Grolsch": "Organization",
      "vrijdag": "Date", "€350M": "Money"}),
    ("De trein van 7:15 naar Roosendaal vertrok met twintig minuten "
     "vertraging.",
     {"7:15": "Time", "Roosendaal": "Location"}),
    ("Mevrouw Schimmelpenninck verkocht de boerderij aan een advocaat "
     "uit Assen voor veel te weinig.",
     {"Schimmelpenninck": "Person", "Assen": "Location"}),
    ("Volgens het rapport van Aegon groeiden de premies met 4.2% in "
     "oktober.",
     {"Aegon": "Organization", "4.2%": "Percentage", "oktober": "Date"}),
    ("De burgemeester van Kampen opende de brug op een regenachtige "
     "zaterdag.",
     {"Kampen": "Location", "zaterdag": "Date"}),
    ("Thijmen Bronkhorst, violist en af en toe smokkelaar, stierf "
     "berooid in Marseille.",
     {"Thijmen": "Person", "Bronkhorst": "Person",
      "Marseille": "Location"}),
    ("De storm legde half Oostende plat in de nacht van dinsdag.",
     {"Oostende": "Location", "dinsdag": "Date"}),
    ("ASML plaatste groene obligaties voor €750M met een vraag die het "
     "aanbod verdrievoudigde.",
     {"ASML": "Organization", "€750M": "Money"}),
    ("Het manuscript belandde bij uitgeverij Querido, verpakt in bruin "
     "papier.",
     {"Querido": "Organization"}),
    ("We spreken af om 19:30 op station Amersfoort, onder de klok.",
     {"19:30": "Time", "Amersfoort": "Location"}),
    ("De jeugdwerkloosheid daalde voor het eerst sinds 2008 tot onder "
     "de 27%.",
     {"2008": "Date", "27%": "Percentage"}),
    ("Hannelore Vercruysse stak de grens over bij Wuustwezel met de "
     "papieren van haar zus.",
     {"Hannelore": "Person", "Vercruysse": "Person",
      "Wuustwezel": "Location"}),
    ("De bestelling kostte €89 en kwam kapot aan; niemand reageert "
     "sinds woensdag.",
     {"€89": "Money", "woensdag": "Date"}),
    ("Fugro presenteerde cijfers op 2021-11-04 en het aandeel steeg "
     "12%.",
     {"Fugro": "Organization", "2021-11-04": "Date", "12%": "Percentage"}),
    ("Commissaris Scarpetta geloofde niet in toeval, zeker niet in "
     "Napels.",
     {"Scarpetta": "Person", "Napels": "Location"}),
    ("Mijn vlucht naar Kreta vertrekt om 6:10 en ik heb nog niet "
     "gepakt.",
     {"Kreta": "Location", "6:10": "Time"}),
    ("De oogst van 2019 was de slechtste in decennia voor de telers in "
     "de Betuwe.",
     {"2019": "Date", "Betuwe": "Location"}),
    ("De minister kondigde in Brussel aan dat Nederland €120M aan het "
     "fonds zou bijdragen.",
     {"Brussel": "Location", "Nederland": "Location", "€120M": "Money"}),
    ("Meneer Koopmans kwam elke zondag om 9:00 met de krant onder zijn "
     "arm.",
     {"Koopmans": "Person", "zondag": "Date", "9:00": "Time"}),
    ("De mist hing tot laat in de ochtend boven Sneek.",
     {"Sneek": "Location"}),
    ("De jury kende de prijs unaniem toe aan Marieke Rijneveld.",
     {"Marieke": "Person", "Rijneveld": "Person"}),
    ("De export naar Portugal daalde 9% in het eerste halfjaar.",
     {"Portugal": "Location", "9%": "Percentage"}),
    ("Tante Aaltje bewaarde €3,000 in een koektrommel boven op de kast.",
     {"Aaltje": "Person", "€3,000": "Money"}),
    ("De bus van Goes naar Middelburg doet er nog geen uur over.",
     {"Goes": "Location", "Middelburg": "Location"}),
    ("Jumbo opent veertig filialen in Vlaanderen voor november.",
     {"Jumbo": "Organization", "Vlaanderen": "Location",
      "november": "Date"}),
    ("Hoogleraar Buitendijk diende op 14/06/2022 zijn ontslag in zonder "
     "toelichting.",
     {"Buitendijk": "Person", "14/06/2022": "Date"}),
    ("We verdwaalden in de steegjes van Brugge op zoek naar het huis "
     "van de smid.",
     {"Brugge": "Location"}),
    ("De audit van KPMG vond een gat van 2.8% in de boeken.",
     {"KPMG": "Organization", "2.8%": "Percentage"}),
    ("Geertruida Boomsma zong één keer in het Concertgebouw, in 1974.",
     {"Geertruida": "Person", "Boomsma": "Person",
      "Concertgebouw": "Location", "1974": "Date"}),
    ("Een kilo tomaten kostte €4 op de markt van Venlo.",
     {"€4": "Money", "Venlo": "Location"}),
    ("Zaterdag sloten ze de haven van Delfzijl wegens de storm.",
     {"Zaterdag": "Date", "Delfzijl": "Location"}),
    ("ING verlaagde zijn groeiprognose voor België naar 1.9%.",
     {"ING": "Organization", "België": "Location", "1.9%": "Percentage"}),
    ("Voorman Schreuder telde de zakken twee keer voordat hij tekende.",
     {"Schreuder": "Person"}),
    ("Het sneeuwt sinds donderdag in Drenthe en er is geen strooiwagen "
     "te zien.",
     {"donderdag": "Date", "Drenthe": "Location"}),
    ("De beurs dekt €1,200 per maand gedurende twee jaar in Uppsala.",
     {"€1,200": "Money", "Uppsala": "Location"}),
    ("De notaris las het testament voor aan de gebroeders Wttewaall om "
     "precies 16:00.",
     {"Wttewaall": "Person", "16:00": "Time"}),
    ("PostNL verhuisde zijn sorteercentrum vorig jaar naar Nieuwegein.",
     {"PostNL": "Organization", "Nieuwegein": "Location"}),
    ("De documentaire over Appel gaat op 03/10/2024 in première in "
     "Rotterdam.",
     {"Appel": "Person", "03/10/2024": "Date", "Rotterdam": "Location"}),
    ("Ik verloor mijn telefoon in een taxi in Luik en niemand bracht "
     "hem terug.",
     {"Luik": "Location"}),
    ("De hotelbezetting in Zandvoort haalde 92% in augustus.",
     {"Zandvoort": "Location", "92%": "Percentage", "augustus": "Date"}),
    ("Sergeant Duyvestein vroeg om 2:20 's nachts om versterking.",
     {"Duyvestein": "Person", "2:20": "Time"}),
    ("Bavaria sponsort het dorpsfeest al sinds 1998.",
     {"Bavaria": "Organization", "1998": "Date"}),
    ("De lift is al sinds dinsdag kapot en de beheerder reageert niet.",
     {"dinsdag": "Date"}),
    ("Liesbeth Overmars liet een briefje en €50 achter op de tafel.",
     {"Liesbeth": "Person", "Overmars": "Person", "€50": "Money"}),
    ("De wandelroute door de Ardennen is prachtig eind maart.",
     {"Ardennen": "Location", "maart": "Date"}),
    ("Ballast Nedam herfinancierde zijn schuld met een korting van 35%.",
     {"Ballast": "Organization", "Nedam": "Organization",
      "35%": "Percentage"}),
    ("De verrekijker van kapitein Terhorst dook op bij een antiquair in "
     "Gent.",
     {"Terhorst": "Person", "Gent": "Location"}),
    ("Er is vrijdags markt op het plein vanaf 8:00.", {"8:00": "Time"}),
    ("Picnic bezorgde vorig jaar meer dan een miljoen bestellingen in "
     "Utrecht.",
     {"Picnic": "Organization", "Utrecht": "Location"}),
    ("Het pensioen van mevrouw Zonneveld komt niet boven de €900 uit.",
     {"Zonneveld": "Person", "€900": "Money"}),
    ("De brand verwoestte in juli tweehonderd hectare bij Ommen.",
     {"juli": "Date", "Ommen": "Location"}),
    ("KBC rekent voor volgend jaar op een inflatie van 5.4%.",
     {"KBC": "Organization", "5.4%": "Percentage"}),
    ("Meubelmaker Steenbergen deed drie maanden over de restauratie "
     "van de kist.",
     {"Steenbergen": "Person"}),
    ("We kwamen op een zondagmiddag aan in Maastricht, bezweet en moe.",
     {"Maastricht": "Location"}),
    ("De entree van het museum kost €12 en op maandag is het gratis.",
     {"€12": "Money", "maandag": "Date"}),
    ("Gasunie legde de compressor stil na de lekkage bij het station.",
     {"Gasunie": "Organization"}),
    ("Juf Hendrika Feenstra leerde drie generaties van het dorp lezen.",
     {"Hendrika": "Person", "Feenstra": "Person"}),
    ("De markt opent om 7:30 en het beste is voor 9:00 al weg.",
     {"7:30": "Time", "9:00": "Time"}),
    ("Twee op de drie ondervraagden in Leeuwarden steunen het "
     "autovrije plan.",
     {"Leeuwarden": "Location"}),
    ("ABN sloot driehonderd plattelandskantoren ondanks de protesten.",
     {"ABN": "Organization"}),
    ("De storm joeg op 2023-01-17 golven van zes meter op de kust van "
     "Zeeland.",
     {"2023-01-17": "Date", "Zeeland": "Location"}),
    ("Vertaler Hoornweg werkte twintig jaar in Genève zonder Frans te "
     "leren.",
     {"Hoornweg": "Person", "Genève": "Location"}),
    ("We verkochten de hele oogst aan een coöperatie uit Emmeloord.",
     {"Emmeloord": "Location"}),
    ("De energierekening steeg met 18% ten opzichte van februari.",
     {"18%": "Percentage", "februari": "Date"}),
    ("Nederland en Denemarken heropenden woensdag de veerverbinding.",
     {"Nederland": "Location", "Denemarken": "Location",
      "woensdag": "Date"}),
    ("De printer staat sinds 10:40 vast en het rapport moest vandaag "
     "af.",
     {"10:40": "Time"}),
    ("BAM gunde de tramwerken van Kortrijk aan zijn Waalse "
     "dochterbedrijf.",
     {"BAM": "Organization", "Kortrijk": "Location"}),
    ("Mijn buurman Evert houdt postduiven op het dak.",
     {"Evert": "Person"}),
    ("De vlucht van KLM naar Willemstad werd geannuleerd wegens "
     "vulkaanas.",
     {"KLM": "Organization", "Willemstad": "Location"}),
    ("De veiling van het schilderij haalde €2,750,000 in amper acht "
     "minuten.",
     {"€2,750,000": "Money"}),
    ("De haven van Antwerpen verwerkte in 2022 7% meer containers.",
     {"Antwerpen": "Location", "2022": "Date", "7%": "Percentage"}),
    ("Patholoog Westerhof tekende het rapport om 3:55 's nachts.",
     {"Westerhof": "Person", "3:55": "Time"}),
    ("Ik wacht al sinds augustus op het onderdeel voor de vaatwasser.",
     {"augustus": "Date"}),
    ("Coolblue stopte met bezorgen in Charleroi na de nieuwe regels.",
     {"Coolblue": "Organization", "Charleroi": "Location"}),
    ("De nieuwe postbode haalt de Vermeerstraat en de Vondelstraat "
     "door elkaar.",
     {"Vermeerstraat": "Location", "Vondelstraat": "Location"}),
    ("We groeiden 11% in omzet en toch sloten ze de vestiging in "
     "Tilburg.",
     {"11%": "Percentage", "Tilburg": "Location"}),
    ("Violist Szeryng speelde in Scheveningen in de stromende regen.",
     {"Szeryng": "Person", "Scheveningen": "Location"}),
    ("Een overnachting in het landhuis kost €145 in het hoogseizoen.",
     {"€145": "Money"}),
    ("De brandoefening is donderdag om 12:15.",
     {"donderdag": "Date", "12:15": "Time"}),
    ("Tata legde de hoogoven van Velsen stil voor onderhoud.",
     {"Tata": "Organization", "Velsen": "Location"}),
    ("Oude mevrouw Geertje zwoer dat ze de wolf bij de molen had "
     "gezien.",
     {"Geertje": "Person"}),
    ("Van Vlieland naar Terschelling is het maar een uur varen.",
     {"Vlieland": "Location", "Terschelling": "Location"}),
    ("Het sociale tarief geeft grote gezinnen 25% korting.",
     {"25%": "Percentage"}),
    ("We leverden het project op 30/09/2025 op, na twee keer uitstel.",
     {"30/09/2025": "Date"}),
    ("Chef Boerma proefde de stoofpot en vroeg oma Aleida om het "
     "recept.",
     {"Boerma": "Person", "Aleida": "Person"}),
    ("Exact nam tweehonderd ingenieurs aan in Delft.",
     {"Exact": "Organization", "Delft": "Location"}),
    ("Het wrak kwam bij eb bloot te liggen voor de kust van Urk.",
     {"Urk": "Location"}),
    ("Ik betaalde €35 voor een paraplu die dezelfde zaterdag al "
     "kapot was.",
     {"€35": "Money", "zaterdag": "Date"}),
    ("De metrowerken in Brussel zijn volgens het consortium voor 85% "
     "klaar.",
     {"Brussel": "Location", "85%": "Percentage"}),
    ("Smid Harmen Bijlsma smeedde de windwijzer van de kerktoren in "
     "1931.",
     {"Harmen": "Person", "Bijlsma": "Person", "1931": "Date"}),
]
