"""Local scoring parity tests (SURVEY §2.14 local module).

Mirrors reference OpWorkflowModelLocalTest: the local score function's output must match
the engine score() path exactly, record by record.
"""

import numpy as np
import pytest

from transmogrifai_tpu import (
    BinaryClassificationModelSelector,
    Dataset,
    FeatureBuilder,
    Workflow,
    transmogrify,
)
from transmogrifai_tpu.local import score_function
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.readers.files import DataReaders
from transmogrifai_tpu.types import PickList, Real, RealNN


@pytest.fixture(scope="module")
def model_and_records():
    rng = np.random.default_rng(3)
    n = 400
    x1 = rng.normal(0, 1, n)
    color = rng.choice(["red", "green", "blue"], n)
    age = np.where(rng.random(n) < 0.15, None, rng.normal(40, 10, n))
    y = (rng.random(n) < 1 / (1 + np.exp(-(1.5 * x1 + (color == "red"))))).astype(float)
    records = [
        {"label": float(y[i]), "x1": float(x1[i]), "color": str(color[i]),
         "age": None if age[i] is None else float(age[i])}
        for i in range(n)
    ]

    label = FeatureBuilder.RealNN("label").extract_field().as_response()
    f_x1 = FeatureBuilder.Real("x1").extract_field().as_predictor()
    f_color = FeatureBuilder.PickList("color").extract_field().as_predictor()
    f_age = FeatureBuilder.Real("age").extract_field().as_predictor()

    vec = transmogrify([f_x1, f_color, f_age])
    checked = label.sanity_check(vec)
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        models=[(LogisticRegression(), [{"reg_param": 0.01}])])
    pred = label.transform_with(sel, checked)

    import pandas as pd

    df = pd.DataFrame(records)
    wf = (Workflow().set_result_features(label, pred)
          .set_reader(DataReaders.Simple.dataframe(df)))
    model = wf.train()
    return model, records, df, label, pred


class TestLocalScoring:
    def test_single_record_shape(self, model_and_records):
        model, records, df, label, pred = model_and_records
        scorer = score_function(model)
        out = scorer(records[0])
        assert pred.name in out
        pmap = out[pred.name]
        assert "prediction" in pmap
        assert any(k.startswith("probability") for k in pmap)

    def test_parity_with_engine_score(self, model_and_records):
        model, records, df, label, pred = model_and_records
        scorer = score_function(model)
        local_out = scorer.batch(records[:50])
        ds = DataReaders.Simple.dataframe(df.head(50)).generate_dataset(
            [f for f in _raws(model)])
        engine = model.score(ds)
        prob = engine[pred.name].prob
        for i, rec_out in enumerate(local_out):
            pm = rec_out[pred.name]
            np.testing.assert_allclose(pm["probability_1"], prob[i, 1], rtol=1e-6)

    def test_single_equals_batch(self, model_and_records):
        model, records, *_ = model_and_records
        scorer = score_function(model)
        single = [scorer(r) for r in records[:5]]
        batch = scorer.batch(records[:5])
        for s, b in zip(single, batch):
            assert s.keys() == b.keys()
            for k in s:
                if isinstance(s[k], dict):
                    for kk in s[k]:
                        assert s[k][kk] == pytest.approx(b[k][kk], rel=1e-9)

    def test_missing_values_handled(self, model_and_records):
        model, records, df, label, pred = model_and_records
        scorer = score_function(model)
        out = scorer({"label": 0.0, "x1": 0.2, "color": None, "age": None})
        assert pred.name in out

    def test_scoring_without_label(self, model_and_records):
        """Inference records have no response field (reference local serving path)."""
        model, records, df, label, pred = model_and_records
        scorer = score_function(model)
        out = scorer({"x1": 0.2, "color": "red", "age": 33.0})
        assert pred.name in out
        assert "prediction" in out[pred.name]

    def test_throughput_smoke(self, model_and_records):
        """Local batch path must be comfortably faster than per-record calls."""
        import time

        model, records, *_ = model_and_records
        scorer = score_function(model)
        scorer.batch(records)  # warm
        t0 = time.perf_counter()
        scorer.batch(records)
        batch_t = time.perf_counter() - t0
        t0 = time.perf_counter()
        for r in records[:20]:
            scorer(r)
        single_t = (time.perf_counter() - t0) / 20 * len(records)
        assert batch_t < single_t


def _raws(model):
    seen = {}
    for f in model.result_features:
        for r in f.raw_features():
            seen.setdefault(r.uid, r)
    return list(seen.values())


class TestLatency:
    def test_single_record_latency(self, model_and_records):
        """The local scorer must serve single records in milliseconds (the
        reference ships MLeap specifically for this; VERDICT r1 weak #8)."""
        import time

        model, records = model_and_records[0], model_and_records[1]
        scorer = score_function(model)
        scorer(records[0])  # warm any lazy paths
        times = []
        for r in records[:50]:
            t0 = time.perf_counter()
            scorer(r)
            times.append(time.perf_counter() - t0)
        p50 = sorted(times)[len(times) // 2]
        assert p50 < 0.05, f"p50 single-record latency {p50*1e3:.1f}ms >= 50ms"

    def test_batch_faster_than_singles(self, model_and_records):
        import time

        model, records = model_and_records[0], model_and_records[1]
        scorer = score_function(model)
        scorer.batch(records[:100])
        t0 = time.perf_counter()
        scorer.batch(records[:100])
        batch_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        for r in records[:100]:
            scorer(r)
        singles_dt = time.perf_counter() - t0
        assert batch_dt < singles_dt / 3, (batch_dt, singles_dt)


class TestStandaloneExport:
    """Numpy-only scoring export (VERDICT r3 #10, the MLeap-bundle role):
    the generated scorer must round-trip score_function's outputs within
    1e-6 in a SUBPROCESS that never imports jax or the framework."""

    def _pipeline(self, winner: str):
        from transmogrifai_tpu import (BinaryClassificationModelSelector,
                                       Dataset, FeatureBuilder, Workflow,
                                       transmogrify)
        from transmogrifai_tpu.models.logistic import LogisticRegression
        from transmogrifai_tpu.models.trees import \
            GradientBoostedTreesClassifier
        from transmogrifai_tpu.types import (MultiPickList, PickList, Real,
                                             RealNN)

        rng = np.random.default_rng(9)
        n = 1200
        x1 = rng.normal(size=n)
        x2 = rng.normal(size=n)
        color = rng.choice(["red", "green", "blue"], n)
        tags = [sorted(rng.choice(["wifi", "pool", "gym", "spa"],
                                  rng.integers(0, 3), replace=False))
                for _ in range(n)]
        if winner == "trees":  # xor-ish signal only trees can fit
            label = ((x1 * x2 > 0) ^ (rng.random(n) < 0.05)).astype(float)
            models = [(GradientBoostedTreesClassifier(),
                       [{"num_rounds": 15, "max_depth": 3}])]
        else:
            label = (x1 - 0.5 * x2 + rng.normal(scale=0.3, size=n) > 0
                     ).astype(float)
            models = [(LogisticRegression(), [{"reg_param": 0.01}])]
        cols = {"x1": x1.tolist(), "x2": x2.tolist(),
                "color": color.tolist(), "tags": tags,
                "label": label.tolist()}
        ds = Dataset.from_features(cols, {"x1": Real, "x2": Real,
                                          "color": PickList,
                                          "tags": MultiPickList,
                                          "label": RealNN})
        lab = FeatureBuilder.of("label", RealNN).extract_field().as_response()
        feats = [
            FeatureBuilder.of("x1", Real).extract_field().as_predictor(),
            FeatureBuilder.of("x2", Real).extract_field().as_predictor(),
            FeatureBuilder.of("color", PickList).extract_field()
            .as_predictor(),
            FeatureBuilder.of("tags", MultiPickList).extract_field()
            .as_predictor()]
        checked = lab.sanity_check(transmogrify(feats))
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2, models=models)
        pred = lab.transform_with(sel, checked)
        return Workflow().set_input_dataset(ds) \
            .set_result_features(lab, pred).train()

    def _roundtrip(self, winner, tmp_path):
        import json
        import os
        import subprocess
        import sys

        from transmogrifai_tpu.local import export_standalone, score_function

        model = self._pipeline(winner)
        out_dir = str(tmp_path / f"bundle_{winner}")
        export_standalone(model, out_dir)

        rng = np.random.default_rng(10)
        records = [{"x1": float(rng.normal()), "x2": float(rng.normal()),
                    "color": str(rng.choice(["red", "green", "blue",
                                             "violet"])),
                    "tags": sorted(str(t) for t in rng.choice(
                        ["wifi", "pool", "gym", "sauna"],
                        rng.integers(0, 3), replace=False))}
                   for _ in range(64)]
        records[0]["x1"] = None  # missing numeric -> fitted fill
        records[1]["color"] = None  # missing categorical -> null slot
        records[2]["tags"] = []  # empty multi-select -> null slot

        # in-process reference via the framework scorer
        scorer = score_function(model)
        ref = scorer.batch(records)
        ref_p1 = []
        for row in ref:
            pmap = [v for v in row.values() if isinstance(v, dict)][0]
            ref_p1.append(pmap["probability_1"])

        driver = (
            "import json, sys\n"
            "sys.path.insert(0, '.')\n"
            "from scorer import Scorer\n"
            "records = json.load(open('records.json'))\n"
            "out = Scorer().score(records)\n"
            "assert 'jax' not in sys.modules\n"
            "assert not any(m.startswith('transmogrifai') "
            "for m in sys.modules)\n"
            "json.dump(out, open('out.json', 'w'))\n")
        with open(os.path.join(out_dir, "records.json"), "w") as fh:
            json.dump(records, fh)
        env = {k: v for k, v in os.environ.items()
               if k not in ("PYTHONPATH",)}
        r = subprocess.run([sys.executable, "-c", driver], cwd=out_dir,
                           env=env, capture_output=True, timeout=120)
        assert r.returncode == 0, r.stderr.decode()[-2000:]
        got = json.load(open(os.path.join(out_dir, "out.json")))
        assert len(got) == len(records)
        got_p1 = [row["probability"][1] for row in got]
        np.testing.assert_allclose(got_p1, ref_p1, atol=1e-6)

    def test_linear_pipeline_round_trips(self, tmp_path):
        self._roundtrip("linear", tmp_path)

    def test_tree_pipeline_round_trips(self, tmp_path):
        self._roundtrip("trees", tmp_path)

    def test_unsupported_stage_raises(self, tmp_path):
        from transmogrifai_tpu import (Dataset, FeatureBuilder, Workflow)
        from transmogrifai_tpu.local import export_standalone
        from transmogrifai_tpu.types import RealNN, Text

        # NER output is a map feature — not a linear+tree serving surface
        from transmogrifai_tpu.data.dataset import Column
        ds = Dataset({"t": Column.from_values(
            Text, ["Alice went to Paris", "Bob stayed home"])})
        t = FeatureBuilder.of("t", Text).extract_field().as_predictor()
        tagged = t.name_entity_tags()
        model = Workflow().set_input_dataset(ds) \
            .set_result_features(tagged).train()
        with pytest.raises(ValueError, match="standalone export"):
            export_standalone(model, str(tmp_path / "nope"))
