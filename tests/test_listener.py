"""Metrics listener + profiling hooks (SURVEY §5.1 OpSparkListener equivalent)."""

import json
import os

import numpy as np

from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.ops.numeric import NumericVectorizer
from transmogrifai_tpu.types import Real, RealNN
from transmogrifai_tpu.utils.listener import (
    AppMetrics,
    OpMetricsListener,
    StageMetrics,
    add_listener,
    remove_listener,
)


def _tiny_workflow():
    rng = np.random.default_rng(0)
    ds = Dataset.from_features(
        {"x": rng.normal(size=20).tolist(), "label": (rng.random(20) > 0.5).astype(float).tolist()},
        {"x": Real, "label": RealNN})
    x = FeatureBuilder.of("x", Real).extract_field().as_predictor()
    vec = x.transform_with(NumericVectorizer())
    wf = Workflow().set_input_dataset(ds).set_result_features(vec)
    return wf, ds, vec


class TestListenerCollection:
    def test_collects_fit_and_transform_metrics(self):
        listener = add_listener(OpMetricsListener())
        try:
            wf, ds, vec = _tiny_workflow()
            model = wf.train()
            model.score(ds)
        finally:
            remove_listener(listener)
        phases = {(m.stage_class, m.phase) for m in listener.metrics.stage_metrics}
        assert ("NumericVectorizer", "fit") in phases
        assert ("NumericVectorizerModel", "transform") in phases
        for m in listener.metrics.stage_metrics:
            assert m.wall_ms >= 0
            assert m.n_rows == 20
            assert m.stage_uid

    def test_no_listener_no_collection(self):
        wf, ds, _ = _tiny_workflow()
        wf.train()  # must not raise or collect anywhere

    def test_app_metrics_serde(self):
        m = AppMetrics(run_type="train", started_at=1.0, ended_at=3.5)
        m.stage_metrics.append(StageMetrics(
            stage_uid="u1", stage_class="C", operation_name="op", phase="fit",
            wall_ms=5.0, n_rows=10, n_cols_in=2, n_cols_out=3, started_at=1.0))
        d = json.loads(m.to_json())
        assert d["appDurationMs"] == 2500.0
        assert d["stageMetrics"][0]["stage_uid"] == "u1"

    def test_log_mode(self, caplog):
        import logging
        listener = add_listener(OpMetricsListener(log_stage_metrics=True,
                                                  collect_stage_metrics=False))
        try:
            with caplog.at_level(logging.INFO, logger="transmogrifai_tpu.metrics"):
                wf, _, _ = _tiny_workflow()
                wf.train()
        finally:
            remove_listener(listener)
        assert listener.metrics.stage_metrics == []
        assert any("NumericVectorizer" in r.message for r in caplog.records)


class TestRunnerIntegration:
    def test_runner_exports_app_metrics(self, tmp_path):
        from transmogrifai_tpu.params import OpParams
        from transmogrifai_tpu.workflow.runner import RunType, WorkflowRunner

        wf, ds, vec = _tiny_workflow()
        metrics_path = os.path.join(tmp_path, "metrics.json")
        model_path = os.path.join(tmp_path, "model")
        runner = WorkflowRunner(workflow=wf)
        params = OpParams(model_location=model_path,
                          metrics_location=metrics_path,
                          collect_stage_metrics=True)
        result = runner.run(RunType.TRAIN, params)
        assert "appMetrics" in result.metrics
        app = result.metrics["appMetrics"]
        assert app["runType"] == "train"
        assert len(app["stageMetrics"]) > 0
        with open(metrics_path) as fh:
            on_disk = json.load(fh)
        assert on_disk["metrics"]["appMetrics"]["stageMetrics"]

    def test_listener_removed_after_run(self, tmp_path):
        from transmogrifai_tpu.params import OpParams
        from transmogrifai_tpu.utils.listener import active_listeners
        from transmogrifai_tpu.workflow.runner import RunType, WorkflowRunner

        wf, _, _ = _tiny_workflow()
        runner = WorkflowRunner(workflow=wf)
        params = OpParams(model_location=os.path.join(tmp_path, "m"),
                          collect_stage_metrics=True)
        runner.run(RunType.TRAIN, params)
        assert active_listeners() == []
