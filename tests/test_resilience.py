"""Training resilience (PR 20): durable sweep journal, bounded retries,
graceful-degradation ladders, SIGKILL resume.

The acceptance story: a training run killed mid-sweep and re-invoked with
the same resume dir skips every committed fold-block (journal hit counters
prove it), produces a bitwise-identical final model (winner, weights, CV
metrics), and performs zero extra backend compiles on the warm resume.  A
persistent device fault under a mesh completes on the dp-halved mesh; an
injected OOM completes at the next-smaller row bucket; a non-retryable
error fails fast with the journal intact.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.data.dataset import Column, Dataset
from transmogrifai_tpu.evaluators.base import BinaryClassificationEvaluator
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.models.selector import (
    BinaryClassificationModelSelector,
    ModelSelector,
)
from transmogrifai_tpu.models.tuning import CrossValidator
from transmogrifai_tpu.obs import flight as obs_flight
from transmogrifai_tpu.perf import measure_compiles
from transmogrifai_tpu.serve.faults import FaultHarness, TransientScoringError
from transmogrifai_tpu.types import OPVector, RealNN
from transmogrifai_tpu.workflow import resilience
from transmogrifai_tpu.workflow.resilience import (
    RetryableTrainingError,
    RetryPolicy,
    SweepJournal,
    resilient_training,
    retry_call,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: no-sleep policy: every retry/backoff unit here asserts on the retry
#: LOGIC, not the wall clock
FAST = dict(policy=RetryPolicy(sleep=lambda s: None))


def _binary_ds(n=400, d=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ rng.normal(size=d))))) \
        .astype(np.float64)
    label = FeatureBuilder.of("label", RealNN).extract_field().as_response()
    vec = FeatureBuilder.of("v", OPVector).extract_field().as_predictor()
    ds = Dataset({"label": Column.from_values(RealNN, y.tolist()),
                  "v": Column.vector(x)})
    return ds, label, vec


def _two_family_selector():
    return BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2,
        models=[(LogisticRegression(),
                 [{"reg_param": 0.001}, {"reg_param": 0.01}]),
                (LogisticRegression(), [{"reg_param": 0.1}])])


def _fit_selector(selector, ds, label, vec):
    label.transform_with(selector, vec)
    return selector.fit(ds)


# ---------------------------------------------------------------------------
# SweepJournal durability
# ---------------------------------------------------------------------------

class TestSweepJournal:
    def test_roundtrip_is_bitwise_with_dtype(self, tmp_path):
        j = SweepJournal(str(tmp_path / "j.json"))
        key = resilience.sweep_block_key(
            "Fam", [{"a": 1}], (3, 42, True), "auPR", "digest", ("mesh",))
        assert j.load(key) is None and j.misses == 1
        for dtype in (np.float32, np.float64):
            scores = np.array([[0.1, 1 / 3], [np.nan, -0.0]], dtype=dtype)
            j.commit(key, scores, family="Fam")
            back = j.load(key)
            assert back.dtype == dtype
            np.testing.assert_array_equal(back, scores)  # NaN/−0.0 exact
        assert j.hits == 2 and j.commits == 2

    def test_zero_byte_garbage_and_non_dict_read_as_empty(self, tmp_path):
        path = tmp_path / "j.json"
        j = SweepJournal(str(path))
        for content in ("", "{truncated", "[1, 2, 3]", "null"):
            path.write_text(content)
            assert j.load("anything") is None
            assert j.keys() == []
        # and a commit over the garbage heals the store
        j.commit("k", np.ones((1, 1)))
        assert j.load("k") is not None

    def test_stale_tmp_is_dropped_not_adopted(self, tmp_path):
        path = tmp_path / "j.json"
        j = SweepJournal(str(path))
        j.commit("k", np.ones((1, 1)))
        (tmp_path / "j.json.tmp").write_text('{"k2": "torn"}')
        assert j.load("k2") is None           # the torn commit never landed
        assert not (tmp_path / "j.json.tmp").exists()
        assert j.load("k") is not None        # the real store is untouched

    def test_key_covers_full_block_identity(self):
        base = dict(family="F", grids=[{"a": 1}], fold_spec=(3, 42, True),
                    metric="auPR", digest="d", mesh_token=None, block="all")

        def key(**over):
            kw = {**base, **over}
            return resilience.sweep_block_key(
                kw["family"], kw["grids"], kw["fold_spec"], kw["metric"],
                kw["digest"], kw["mesh_token"], block=kw["block"])

        ref = key()
        assert key() == ref  # deterministic
        for over in (dict(family="G"), dict(grids=[{"a": 2}]),
                     dict(fold_spec=(5, 42, True)), dict(metric="logLoss"),
                     dict(digest="other"), dict(mesh_token=("m", 4)),
                     dict(block="fold0")):
            assert key(**over) != ref, over

    def test_data_digest_distinguishes_dtype_shape_content(self):
        a = np.arange(6, dtype=np.float32)
        assert resilience.data_digest(a) == resilience.data_digest(a.copy())
        assert resilience.data_digest(a) != resilience.data_digest(
            a.astype(np.float64))
        assert resilience.data_digest(a) != resilience.data_digest(
            a.reshape(2, 3))
        assert resilience.data_digest(a, None) != resilience.data_digest(a)


# ---------------------------------------------------------------------------
# Satellite 1: zero-byte / torn state is "no checkpoint", not a decode error
# ---------------------------------------------------------------------------

class TestCheckpointHardening:
    def test_offset_checkpoint_zero_byte_is_no_checkpoint(self, tmp_path):
        from transmogrifai_tpu.readers import OffsetCheckpoint

        path = tmp_path / "offsets.json"
        ckpt = OffsetCheckpoint(str(path))
        for content in ("", "{torn", "[]", '"str"'):
            path.write_text(content)
            assert ckpt.load("src") == 0
            assert ckpt.load("src", default=7) == 7
            assert ckpt.load_meta("src") is None
        # commit over the corrupt state starts fresh instead of raising
        path.write_text("[1,2]")
        ckpt.commit("src", 3)
        assert ckpt.load("src") == 3

    def test_empty_current_pointer_is_no_promoted_checkpoint(self, tmp_path):
        from transmogrifai_tpu.workflow.continual import RefitController

        d = tmp_path / "ckpt"
        d.mkdir()
        for content in ("", "   \n"):
            (d / "CURRENT").write_text(content)
            assert RefitController.load_checkpoint(str(d)) is None


# ---------------------------------------------------------------------------
# retry_call: bounded backoff, typed classification, fail-fast
# ---------------------------------------------------------------------------

class TestRetryCall:
    def test_passthrough_without_active_context(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise RetryableTrainingError("transient")
            return "ok"

        # inactive: even a retryable error propagates (old behavior)
        with pytest.raises(RetryableTrainingError):
            retry_call(fn, "stage_fit")

    def test_retries_then_succeeds_with_backoff_and_diagnostics(self):
        delays = []
        policy = RetryPolicy(backoff_base_s=0.05, backoff_cap_s=2.0,
                             jitter=0.0, sleep=delays.append)
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise RetryableTrainingError("transient")
            return "ok"

        with resilient_training(policy=policy) as res:
            assert retry_call(fn, "ingest_chunk", chunk=4) == "ok"
        assert len(calls) == 3 and res.retries == 2
        assert delays == [0.05, 0.1]  # min(cap, base * 2**(attempt-1))
        assert [d.code for d in res.diagnostics] == ["TM820", "TM820"]

    def test_exhaustion_raises_the_last_error(self):
        with resilient_training(**FAST) as res:
            with pytest.raises(RetryableTrainingError, match="always"):
                retry_call(lambda: (_ for _ in ()).throw(
                    RetryableTrainingError("always")), "prefetch")
        assert res.retries == res.policy.max_retries

    def test_non_retryable_fails_fast_with_tm823(self):
        with resilient_training(**FAST) as res:
            with pytest.raises(ValueError, match="corrupt"):
                retry_call(lambda: (_ for _ in ()).throw(
                    ValueError("corrupt")), "stage_fit")
        assert res.retries == 0
        assert [d.code for d in res.diagnostics] == ["TM823"]

    def test_fail_fast_reported_once_across_nested_wrappers(self):
        """The same non-retryable exception propagates through every
        enclosing retry_call (device_sync -> stage_fit in a real train);
        TM823 must fire once, at the innermost point."""
        with resilient_training(**FAST) as res:
            def inner():
                raise ValueError("corrupt")

            with pytest.raises(ValueError, match="corrupt"):
                retry_call(lambda: retry_call(inner, "device_sync"),
                           "stage_fit")
        assert [d.code for d in res.diagnostics] == ["TM823"]
        assert "device_sync" in res.diagnostics[0].message

    def test_context_stack_is_nested_lifo_and_last_survives(self):
        assert resilience.active() is None
        with resilient_training() as outer:
            assert resilience.active() is outer
            with resilient_training() as inner:
                assert resilience.active() is inner
            assert resilience.active() is outer
            assert resilience.last() is inner
        assert resilience.active() is None
        assert resilience.last() is outer


# ---------------------------------------------------------------------------
# Graceful degradation ladders
# ---------------------------------------------------------------------------

class TestDegradationLadders:
    def test_persistent_mesh_fault_completes_on_shrunk_mesh(self):
        """ISSUE acceptance: a transient device failure that persists on the
        dp=4 mesh exhausts in-place retries, degrades to the dp=2 twin
        (predicate no longer matches), and the sweep completes with finite
        metrics, a TM821 diagnostic, and a degrade_mesh_shrink event."""
        from transmogrifai_tpu.parallel.mesh import make_mesh, use_mesh

        ds, label, vec = _binary_ds(n=512, seed=3)
        selector = ModelSelector(
            models=[(LogisticRegression(),
                     [{"reg_param": 0.001}, {"reg_param": 0.01}])],
            validator=CrossValidator(BinaryClassificationEvaluator(),
                                     num_folds=2))
        harness = FaultHarness().fail_when(
            "sweep_dispatch", lambda ctx: ctx.get("dp") == 4,
            lambda: TransientScoringError("unavailable: injected device "
                                          "fault"))
        rec = obs_flight.install_recorder(obs_flight.FlightRecorder())
        try:
            with use_mesh(make_mesh(4, 2)), harness, \
                    resilient_training(**FAST) as res:
                model = _fit_selector(selector, ds, label, vec)
        finally:
            obs_flight.uninstall_recorder()
        assert res.degradations == [{
            "kind": "mesh_shrink", "family": "LogisticRegression",
            "dp_from": 4, "dp_to": 2}]
        assert "TM821" in [d.code for d in res.diagnostics]
        events = rec.events("degrade_mesh_shrink")
        assert len(events) == 1
        assert events[0]["data"]["dp_from"] == 4
        assert events[0]["data"]["dp_to"] == 2
        vals = [v for ev in model.summary.validation_results
                for v in ev.metric_values]
        assert vals and np.isfinite(vals).all()

    def test_repeated_oom_completes_at_next_smaller_bucket(self):
        """ISSUE acceptance: resource exhaustion at 1000 rows skips straight
        to the 512-row bucket (retrying the same shape cannot help), the
        predicate stops matching, and the sweep completes with TM822 + a
        degrade_bucket_shrink event."""
        ds, label, vec = _binary_ds(n=1000, seed=4)
        selector = ModelSelector(
            models=[(LogisticRegression(), [{"reg_param": 0.01}])],
            validator=CrossValidator(BinaryClassificationEvaluator(),
                                     num_folds=2))
        harness = FaultHarness().fail_when(
            "sweep_dispatch", lambda ctx: ctx.get("rows", 0) > 512,
            lambda: TransientScoringError("RESOURCE_EXHAUSTED: out of "
                                          "memory"))
        rec = obs_flight.install_recorder(obs_flight.FlightRecorder())
        try:
            with harness, resilient_training(**FAST) as res:
                model = _fit_selector(selector, ds, label, vec)
        finally:
            obs_flight.uninstall_recorder()
        assert res.degradations == [{
            "kind": "bucket_shrink", "family": "LogisticRegression",
            "rows_from": 1000, "row_cap": 512}]
        assert "TM822" in [d.code for d in res.diagnostics]
        assert len(rec.events("degrade_bucket_shrink")) == 1
        vals = [v for ev in model.summary.validation_results
                for v in ev.metric_values]
        assert vals and np.isfinite(vals).all()

    def test_degraded_scores_never_commit_under_full_fidelity_key(
            self, tmp_path):
        """A block that completed on capped rows must NOT journal — a
        resumed healthy run has to re-run it at full fidelity."""
        ds, label, vec = _binary_ds(n=1000, seed=4)
        selector = ModelSelector(
            models=[(LogisticRegression(), [{"reg_param": 0.01}])],
            validator=CrossValidator(BinaryClassificationEvaluator(),
                                     num_folds=2))
        journal = SweepJournal(str(tmp_path / "j.json"))
        harness = FaultHarness().fail_when(
            "sweep_dispatch", lambda ctx: ctx.get("rows", 0) > 512,
            lambda: TransientScoringError("resource exhausted"))
        with harness, resilient_training(journal=journal, **FAST) as res:
            _fit_selector(selector, ds, label, vec)
        assert res.degradations  # the ladder did fire
        assert journal.keys() == []

    def test_non_retryable_fails_fast_with_journal_intact(self, tmp_path):
        """ISSUE acceptance: family 1 gathers and commits; family 2's device
        sync raises a NON-retryable error — the fit raises immediately
        (TM823), no ladder, and the journal keeps the completed block."""
        ds, label, vec = _binary_ds(n=300, seed=5)
        selector = _two_family_selector()
        journal = SweepJournal(str(tmp_path / "j.json"))
        harness = FaultHarness().script(
            "device_sync", [None, ValueError("corrupt gather")])
        with harness, resilient_training(journal=journal, **FAST) as res:
            with pytest.raises(ValueError, match="corrupt gather"):
                _fit_selector(selector, ds, label, vec)
        assert [d.code for d in res.diagnostics] == ["TM823"]
        assert res.degradations == []
        assert len(journal.keys()) == 1  # family 1's block survived the fail


# ---------------------------------------------------------------------------
# Durable sweep resume: bitwise-identical, zero warm compiles
# ---------------------------------------------------------------------------

class TestSweepResume:
    def test_killed_sweep_resumes_bitwise_at_zero_compiles(self, tmp_path):
        """The in-process acceptance core: run 1 dies after family 1's block
        committed; run 2 with the same resume dir replays it (journal hit),
        dispatches only the rest, performs ZERO backend compiles, and the
        final model scores bitwise-identically to an uninterrupted run."""
        rng = np.random.default_rng(0)
        x = rng.normal(size=(300, 4)).astype(np.float32)
        y = (rng.random(300) < 0.5).astype(np.float64)

        def build():
            sel = _two_family_selector()
            label = FeatureBuilder.of("label", RealNN).extract_field() \
                .as_response()
            vec = FeatureBuilder.of("v", OPVector).extract_field() \
                .as_predictor()
            pred = label.transform_with(sel, vec)
            ds = Dataset({"label": Column.from_values(RealNN, y.tolist()),
                          "v": Column.vector(x)})
            wf = Workflow().set_result_features(label, pred) \
                .set_input_dataset(ds)
            return wf, ds, pred

        wf_ref, ds_ref, pred_ref = build()
        model_ref = wf_ref.train()
        ref = np.asarray(model_ref.score(ds_ref)[pred_ref.name].prob)

        resume = str(tmp_path / "ckpt")
        harness = FaultHarness().script(
            "device_sync", [None, RuntimeError("injected mid-sweep kill")])
        wf1, _, _ = build()
        with harness:
            with pytest.raises(RuntimeError, match="mid-sweep kill"):
                wf1.train(resume=resume)
        journal_after_kill = SweepJournal(
            os.path.join(resume, "sweep_journal.json"))
        assert len(journal_after_kill.keys()) == 1

        rec = obs_flight.install_recorder(obs_flight.FlightRecorder())
        try:
            wf2, ds2, pred2 = build()
            with measure_compiles() as mc:
                model = wf2.train(resume=resume)
        finally:
            obs_flight.uninstall_recorder()
        res = resilience.last()
        assert res.journal.hits >= 1           # the committed block replayed
        assert mc.backend_compiles == 0        # warm resume compiles nothing
        assert len(rec.events("sweep_block_resume")) >= 1
        out = np.asarray(model.score(ds2)[pred2.name].prob)
        np.testing.assert_array_equal(out, ref)  # bitwise, not approx
        s_ref, s_resumed = model_ref.summary(), model.summary()
        assert s_resumed.best_model_name == s_ref.best_model_name
        assert [e.metric_values for e in s_resumed.validation_results] == \
            [e.metric_values for e in s_ref.validation_results]

    def test_identical_rerun_replays_every_block(self, tmp_path):
        """Same data + same grids + same resume dir: the second run's sweep
        is 100% journal hits and zero commits beyond the first run's."""
        ds, _, _ = _binary_ds(n=300, seed=6)
        resume = str(tmp_path / "ckpt")
        journal_path = os.path.join(resume, "sweep_journal.json")
        os.makedirs(resume)

        def sweep_once():
            _, label, vec = _binary_ds(n=300, seed=6)
            sel = _two_family_selector()
            with resilient_training(journal=SweepJournal(journal_path)):
                _fit_selector(sel, ds, label, vec)
            return resilience.last().journal

        j1 = sweep_once()
        assert j1.commits == 2 and j1.hits == 0
        j2 = sweep_once()
        assert j2.hits == 2 and j2.commits == 0

    def test_workflow_cv_blocks_journal_per_fold(self, tmp_path):
        """The workflow-level CV path journals per (fold, family): k folds x
        one family = k block commits, all replayed on a re-run."""
        ds, _, _ = _binary_ds(n=240, seed=7)
        journal_path = str(tmp_path / "j.json")

        def run_cv():
            _, label, vec = _binary_ds(n=240, seed=7)
            sel = BinaryClassificationModelSelector.with_cross_validation(
                num_folds=3,
                models=[(LogisticRegression(), [{"reg_param": 0.01}])])
            pred = label.transform_with(sel, vec)
            wf = Workflow().with_workflow_cv() \
                .set_result_features(label, pred).set_input_dataset(ds)
            with resilient_training(journal=SweepJournal(journal_path)):
                wf.train()
            return resilience.last().journal

        j1 = run_cv()
        assert j1.commits == 3 and j1.hits == 0, (j1.hits, j1.commits)
        j2 = run_cv()
        assert j2.hits == 3 and j2.commits == 0, (j2.hits, j2.commits)


# ---------------------------------------------------------------------------
# Subprocess SIGKILL end-to-end (the real thing: no atexit, no finally)
# ---------------------------------------------------------------------------

_SIGKILL_SCRIPT = textwrap.dedent("""\
    import json, os, signal, sys

    import numpy as np

    mode, out_dir, resume = sys.argv[1], sys.argv[2], sys.argv[3]

    from transmogrifai_tpu import FeatureBuilder, Workflow
    from transmogrifai_tpu.data.dataset import Column, Dataset
    from transmogrifai_tpu.models.logistic import LogisticRegression
    from transmogrifai_tpu.models.selector import (
        BinaryClassificationModelSelector)
    from transmogrifai_tpu.types import OPVector, RealNN

    rng = np.random.default_rng(7)
    x = rng.normal(size=(240, 4)).astype(np.float32)
    y = (rng.random(240) < 0.5).astype(np.float64)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2,
        models=[(LogisticRegression(),
                 [{"reg_param": 0.001}, {"reg_param": 0.01}]),
                (LogisticRegression(), [{"reg_param": 0.1}])])
    label = FeatureBuilder.of("label", RealNN).extract_field().as_response()
    vec = FeatureBuilder.of("v", OPVector).extract_field().as_predictor()
    pred = label.transform_with(sel, vec)
    ds = Dataset({"label": Column.from_values(RealNN, y.tolist()),
                  "v": Column.vector(x)})
    wf = Workflow().set_result_features(label, pred).set_input_dataset(ds)

    if mode == "kill":
        from transmogrifai_tpu.serve.faults import FaultHarness

        h = FaultHarness()
        # family 1 gathers + commits, then SIGKILL mid family 2: no atexit,
        # no finally, the journal's fsync'd commit is all that survives
        h.script("device_sync",
                 [None, lambda ctx: os.kill(os.getpid(), signal.SIGKILL)])
        with h:
            wf.train(resume=resume)
        raise SystemExit("unreachable: the harness should have killed us")

    model = wf.train(resume=resume) if resume else wf.train()
    probs = np.asarray(model.score(ds)[pred.name].prob)
    np.save(os.path.join(out_dir, "probs.npy"), probs)
    s = model.summary()
    hits = 0
    if resume:
        from transmogrifai_tpu.workflow import resilience

        res = resilience.last()
        hits = res.journal.hits if res and res.journal else 0
    with open(os.path.join(out_dir, "summary.json"), "w") as fh:
        json.dump({
            "winner": s.best_model_name,
            "metrics": [[e.model_name, sorted(e.grid.items()),
                         e.metric_values]
                        for e in s.validation_results],
            "journal_hits": hits,
        }, fh, sort_keys=True)
""")


def _run_sub(script_path, *args, check_rc=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO_ROOT + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    proc = subprocess.run(
        [sys.executable, str(script_path), *map(str, args)],
        capture_output=True, text=True, env=env, timeout=300)
    if check_rc is not None:
        assert proc.returncode == check_rc, (proc.returncode, proc.stderr)
    return proc


class TestSigkillResume:
    def test_sigkill_mid_sweep_resume_is_bitwise_with_journal_hits(
            self, tmp_path):
        """Full acceptance e2e: a REAL SIGKILL lands mid-sweep after one
        fold-block committed; a fresh process with the same resume dir
        replays the block and the final model is bitwise-identical to an
        uninterrupted run's (winner, CV metrics, scored probabilities)."""
        script = tmp_path / "sweep_e2e.py"
        script.write_text(_SIGKILL_SCRIPT)
        resume = tmp_path / "ckpt"
        ref_out = tmp_path / "ref"
        res_out = tmp_path / "resumed"
        ref_out.mkdir(), res_out.mkdir()

        killed = _run_sub(script, "kill", res_out, resume)
        assert killed.returncode == -signal.SIGKILL, killed.stderr
        journal = SweepJournal(str(resume / "sweep_journal.json"))
        assert len(journal.keys()) == 1  # the fsync'd commit survived SIGKILL

        _run_sub(script, "run", res_out, resume, check_rc=0)
        _run_sub(script, "run", ref_out, "", check_rc=0)

        resumed = json.loads((res_out / "summary.json").read_text())
        ref = json.loads((ref_out / "summary.json").read_text())
        assert resumed["journal_hits"] >= 1
        assert resumed["winner"] == ref["winner"]
        assert resumed["metrics"] == ref["metrics"]  # CV metrics, bitwise
        np.testing.assert_array_equal(
            np.load(res_out / "probs.npy"), np.load(ref_out / "probs.npy"))


# ---------------------------------------------------------------------------
# CLI: python -m transmogrifai_tpu.cli train --resume
# ---------------------------------------------------------------------------

class TestCliTrain:
    def test_cli_train_resume_reports_journal_counters(self, tmp_path):
        import pandas as pd

        rng = np.random.default_rng(0)
        n = 200
        x = rng.normal(0, 1, n)
        y = (rng.random(n) < 1 / (1 + np.exp(-2 * x))).astype(float)
        csv = tmp_path / "data.csv"
        pd.DataFrame({"label": y, "x": x}).to_csv(csv, index=False)

        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        proc = subprocess.run(
            [sys.executable, "-m", "transmogrifai_tpu.cli", "train",
             "--input", str(csv), "--response", "label",
             "--model-location", str(tmp_path / "model"),
             "--resume", str(tmp_path / "ckpt"), "--format", "json"],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 0, proc.stderr
        payload = json.loads(proc.stdout.strip().splitlines()[-1])
        assert payload["kind"] == "binary"
        assert payload["journal"]["commits"] >= 1
        assert payload["journal"]["entries"] >= 1
        assert os.path.isdir(tmp_path / "model")
        assert os.path.exists(tmp_path / "ckpt" / "sweep_journal.json")
