"""Registry-wide behavior-spec sweep (VERDICT r1 #6).

Every class in ``STAGE_REGISTRY`` must either
- pass ``assert_transformer_spec`` / ``assert_estimator_spec`` through a case
  built here,
- be the fitted-model product of an estimator case (``assert_estimator_spec``
  runs the fitted model through the full transformer spec), or
- carry an explicit exemption with a reason.

``test_registry_fully_covered`` pins the partition, so adding a stage without
spec coverage fails CI.

Reference: features/.../test/OpTransformerSpec.scala:1-162 (the reference
applies the shared spec to every stage suite), OpEstimatorSpec.scala:55-143.
"""

import base64
import importlib
import pkgutil

import numpy as np
import pytest

import transmogrifai_tpu
from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.data.dataset import Column
from transmogrifai_tpu.stages.base import STAGE_REGISTRY, Estimator, Transformer
from transmogrifai_tpu.testkit.specs import (
    assert_estimator_spec,
    assert_transformer_spec,
)
from transmogrifai_tpu.types import (
    Base64,
    Binary,
    Date,
    DateList,
    DateMap,
    Email,
    Geolocation,
    GeolocationMap,
    Integral,
    MultiPickList,
    OPVector,
    Phone,
    PhoneMap,
    PickList,
    Real,
    RealMap,
    RealNN,
    Text,
    TextList,
    TextMap,
    URL,
)
from transmogrifai_tpu.utils.vector_metadata import (
    VectorColumnMetadata,
    VectorMetadata,
)

# populate the registry: stages register at class-definition time, so every
# module must be imported before the sweep enumerates STAGE_REGISTRY
for _m in pkgutil.walk_packages(transmogrifai_tpu.__path__,
                                prefix="transmogrifai_tpu."):
    if "__main__" not in _m.name:
        importlib.import_module(_m.name)


WED_MS = 1528887600000  # 2018-06-13 11:00 UTC
_DAY = 24 * 3600 * 1000
_PNG = base64.b64encode(b"\x89PNG\r\n\x1a\n" + b"\x00" * 16).decode()
_PDF = base64.b64encode(b"%PDF-1.4 hello").decode()

#: deterministic 12-row sample values per feature type
TYPE_VALUES = {
    RealNN: [0.5, 1.5, 2.5, 0.25, 3.5, 1.0, 2.0, 0.75, 1.25, 2.75, 0.1, 3.0],
    Real: [1.0, None, 3.0, 2.0, None, 5.0, 0.5, 4.0, 2.5, 1.5, None, 3.5],
    Integral: [1, 2, None, 4, 5, 6, 7, None, 9, 10, 11, 12],
    Binary: [True, False, None, True, False, True, False, True, None, False,
             True, False],
    Text: ["alpha", "beta gamma", None, "delta", "epsilon zeta", "eta",
           "theta iota", None, "kappa", "lambda mu", "nu", "xi omicron"],
    PickList: ["red", "blue", "red", None, "green", "blue", "red", "green",
               "blue", "red", None, "green"],
    MultiPickList: [{"x", "y"}, {"x"}, set(), {"y", "z"}, {"z"}, {"x", "z"},
                    {"y"}, set(), {"x", "y", "z"}, {"z"}, {"x"}, {"y"}],
    TextList: [["big", "cat"], ["small", "dog"], [], ["big", "dog"],
               ["small", "cat", "ran"], ["cat"], ["dog", "ran"], [],
               ["big"], ["small"], ["ran", "far"], ["cat", "dog"]],
    Email: ["a@example.com", "b@test.org", None, "bad-email", "c@example.com",
            "d@foo.io", None, "e@bar.net", "f@example.com", "oops@", "g@x.co",
            "h@example.com"],
    URL: ["https://example.com/a", "http://test.org/b?q=1", None, "not a url",
          "https://foo.io", "https://bar.net/x/y", None, "ftp://files.example.com",
          "https://example.com", "nope", "http://x.co", "https://y.dev/z"],
    Phone: ["+14155552671", "4155552671", None, "123", "+442071838750",
            "+81312345678", None, "555-867-5309", "+14155550000", "0",
            "+4930123456", "+14155559999"],
    PhoneMap: [{"home": "+14155552671", "work": "12"} if i % 3 else {}
               for i in range(12)],
    Base64: [_PNG, _PDF, None, _PNG, _PDF, _PNG, None, _PDF, _PNG, _PDF,
             _PNG, _PDF],
    Date: [WED_MS + i * _DAY for i in range(11)] + [None],
    DateList: [[WED_MS, WED_MS + _DAY], [WED_MS + 2 * _DAY], [],
               [WED_MS + i * _DAY for i in range(3)]] * 3,
    DateMap: [{"d1": WED_MS + i * _DAY, "d2": WED_MS - i * _DAY}
              if i % 4 else {} for i in range(12)],
    RealMap: [{"x": float(i), "y": 2.0 * i} if i % 5 else {"x": float(i)}
              for i in range(12)],
    TextMap: [{"k1": ["u", "v", "u", "w"][i % 4], "k2": "c"} if i % 3 else {}
              for i in range(12)],
    GeolocationMap: [{"home": [37.7 + i * 0.1, -122.4 + i * 0.1, 5.0]}
                     if i % 4 else {} for i in range(12)],
    Geolocation: [[37.77 + (i % 5) * 0.2, -122.42 + (i % 3) * 0.3, 5.0]
                  if i % 6 else None for i in range(12)],
}


def _feat(name, ftype, response=False):
    b = FeatureBuilder.of(name, ftype).extract_field()
    return b.as_response() if response else b.as_predictor()


def _typed_ds(specs):
    """specs: list of (name, ftype) -> (Dataset, [features])."""
    cols = {n: TYPE_VALUES[t] for n, t in specs}
    ds = Dataset.from_features(cols, dict(specs))
    return ds, [_feat(n, t) for n, t in specs]


def _label_vector_ds(n=48, d=6, classes=2, nonneg=True):
    """RealNN label + OPVector features with full slot metadata."""
    rng = np.random.default_rng(11)
    x = rng.normal(size=(n, d)).astype(np.float32)
    if nonneg:
        x = np.abs(x)  # NaiveBayes requires non-negative features
    y = rng.integers(0, classes, size=n).astype(float)
    x[:, 0] += y  # signal
    meta = VectorMetadata(
        "v", [VectorColumnMetadata(f"f{j}", "Real") for j in range(d)]
    ).reindexed()
    ds = Dataset({
        "label": Column.from_values(RealNN, list(y)),
        "v": Column.vector(x, meta),
    })
    return ds, _feat("label", RealNN, response=True), _feat("v", OPVector)


def _vector_pair_ds(n=12, d=3):
    rng = np.random.default_rng(3)
    dsd = {}
    feats = []
    for name in ("v1", "v2"):
        meta = VectorMetadata(
            name, [VectorColumnMetadata(f"{name}_f{j}", "Real")
                   for j in range(d)]).reindexed()
        dsd[name] = Column.vector(rng.normal(size=(n, d)).astype(np.float32), meta)
        feats.append(_feat(name, OPVector))
    return Dataset(dsd), feats


# -- named fns for function-param stages (lambdas break copy/get_params eq) ---
def _is_present(v):
    return v is not None


def _over_one(v):
    return v is not None and v > 1.0


def _double(v):
    return None if v is None else 2.0 * v


# --------------------------------------------------------------------------
# case table: stage name -> zero-arg builder returning (stage, dataset, flags)
# --------------------------------------------------------------------------


def unary(tname, cls_kw=None, **flags):
    def build(cls):
        ds, (f,) = _typed_ds([("a", tname)])
        stage = cls(**(cls_kw or {}))
        f.transform_with(stage)
        return stage, ds, flags
    return build


def binary(t1, t2, cls_kw=None, **flags):
    def build(cls):
        ds, (f1, f2) = _typed_ds([("a", t1), ("b", t2)])
        stage = cls(**(cls_kw or {}))
        f1.transform_with(stage, f2)
        return stage, ds, flags
    return build


def seq(tname, k=2, cls_kw=None, **flags):
    def build(cls):
        ds, feats = _typed_ds([(f"c{i}", tname) for i in range(k)])
        stage = cls(**(cls_kw or {}))
        feats[0].transform_with(stage, *feats[1:])
        return stage, ds, flags
    return build


def label_vec(cls_kw=None, classes=2, **flags):
    def build(cls):
        ds, label, vec = _label_vector_ds(classes=classes)
        stage = cls(**(cls_kw or {}))
        label.transform_with(stage, vec)
        return stage, ds, flags
    return build


def label_col(tname, cls_kw=None, **flags):
    def build(cls):
        cols = {"label": TYPE_VALUES[RealNN], "a": TYPE_VALUES[tname]}
        ds = Dataset.from_features(cols, {"label": RealNN, "a": tname})
        label = _feat("label", RealNN, response=True)
        f = _feat("a", tname)
        stage = cls(**(cls_kw or {}))
        label.transform_with(stage, f)
        return stage, ds, flags
    return build


def vec_seq(cls_kw=None, **flags):
    def build(cls):
        ds, feats = _vector_pair_ds()
        stage = cls(**(cls_kw or {}))
        feats[0].transform_with(stage, *feats[1:])
        return stage, ds, flags
    return build


_SMALL_TREES = {"num_trees": 3, "max_depth": 3}
_SMALL_BOOST = {"num_rounds": 3, "max_depth": 3}

CASES = {
    # -- unary transformers ---------------------------------------------------
    "AliasTransformer": unary(Real, {"name": "a_alias"}),
    "ScalarMathTransformer": unary(Real, {"op": "multiply", "scalar": 2.0}),
    "NumericBucketizer": unary(
        Real, {"splits": [0.0, 2.0, 4.0, 6.0], "track_nulls": True}),
    "ScalerTransformer": unary(
        Real, {"scaling_type": "linear", "slope": 2.0, "intercept": 1.0}),
    "TextTokenizer": unary(Text),
    "TextLenTransformer": unary(Text),
    "LanguageDetector": unary(Text),
    "NameEntityRecognizer": unary(Text),
    "EmailToPickList": unary(Email),
    "ValidEmailTransformer": unary(Email),
    "ValidUrlTransformer": unary(URL),
    "UrlToDomainTransformer": unary(URL),
    "PhoneNumberValidator": unary(Phone),
    "ParsePhoneDefaultCountry": unary(Phone),
    "IsValidPhoneDefaultCountry": unary(Phone),
    "IsValidPhoneMapDefaultCountry": unary(PhoneMap),
    "ParsePhoneNumber": binary(Phone, Text),
    "IsValidPhoneNumber": binary(Phone, Text),
    "MimeTypeDetector": unary(Base64),
    "TimePeriodTransformer": unary(Date, {"period": "DayOfWeek"}),
    "TimePeriodListTransformer": unary(DateList, {"period": "DayOfWeek"}),
    "TimePeriodMapTransformer": unary(DateMap, {"period": "DayOfWeek"}),
    "HashingTF": unary(TextList, {"num_features": 32}),
    "NGramTransformer": unary(TextList, {"n": 2}),
    "StopWordsRemover": unary(TextList),
    "LiftToList": unary(
        TextList,
        {"inner": STAGE_REGISTRY["ReplaceTransformer"](
            input_type=Text, old_value="cat", new_value="CAT")},
        check_serde=False),
    "LiftToMap": unary(
        RealMap,
        {"inner": STAGE_REGISTRY["UnaryLambdaTransformer"](
            fn=_double, input_type=Real, output_type=Real)},
        check_serde=False),
    "FilterMap": unary(RealMap),
    "ToOccurTransformer": unary(Real, {"match_fn": _is_present,
                                       "input_type": Real},
                                check_serde=False),
    "ReplaceTransformer": unary(
        Text, {"input_type": Text, "old_value": "beta gamma",
               "new_value": "B"}),
    "ExistsTransformer": unary(Real, {"predicate": _over_one,
                                      "input_type": Real},
                               check_serde=False),
    "FilterTransformer": unary(
        Real, {"predicate": _over_one, "default": -1.0, "input_type": Real},
        check_serde=False),
    "UnaryLambdaTransformer": unary(
        Real, {"fn": _double, "input_type": Real, "output_type": Real},
        check_serde=False),
    "IndexToString": unary(Real, {"labels": ["a", "b", "c", "d"]}),
    "DropIndicesByTransformer": None,  # needs a vector input; built below
    # -- binary transformers --------------------------------------------------
    "BinaryMathTransformer": binary(Real, Real, {"op": "plus"}),
    "DescalerTransformer": None,  # needs a Scaler-produced input; built below
    "SubstringTransformer": binary(Text, Text),
    "NGramSimilarity": binary(Text, Text),
    "JaccardSimilarity": binary(MultiPickList, MultiPickList),
    # -- sequence vectorizers -------------------------------------------------
    "NumericVectorizer": seq(Real),
    "RealNNVectorizer": seq(RealNN),
    "BinaryVectorizer": seq(Binary),
    "OneHotVectorizer": seq(PickList, cls_kw={"top_k": 3, "min_support": 1}),
    "MultiPickListVectorizer": seq(
        MultiPickList, cls_kw={"top_k": 3, "min_support": 1}),
    "SmartTextVectorizer": seq(Text, cls_kw={"max_cardinality": 3,
                                             "num_hashes": 16}),
    "SmartTextMapVectorizer": seq(TextMap, cls_kw={"max_cardinality": 3,
                                                   "num_hashes": 16}),
    "TextMapPivotVectorizer": seq(
        TextMap, cls_kw={"top_k": 2, "min_support": 1}),
    "NumericMapVectorizer": seq(RealMap),
    "GeolocationVectorizer": seq(Geolocation),
    "GeolocationMapVectorizer": seq(GeolocationMap),
    "DateToUnitCircleVectorizer": seq(Date),
    "DateMapToUnitCircleVectorizer": seq(DateMap),
    "DateListVectorizer": seq(DateList),
    "TextListHashingVectorizer": seq(TextList, cls_kw={"num_hashes": 16}),
    "VectorsCombiner": vec_seq(),
    # -- unary estimators -----------------------------------------------------
    "FillMissingWithMean": unary(Real),
    "StandardScaler": unary(RealNN),
    "PercentileCalibrator": unary(RealNN, {"buckets": 4}),
    "StringIndexer": unary(Text, {"handle_invalid": "keep"}),
    "CountVectorizer": unary(TextList, {"min_count": 1, "vocab_size": 8}),
    "LDA": unary(TextList, {"k": 2, "max_iter": 5}),
    "Word2Vec": unary(TextList, {"embedding_dim": 8, "epochs": 2, "min_count": 1}),
    # -- (label, column) estimators -------------------------------------------
    "IsotonicRegressionCalibrator": label_col(RealNN),
    "DecisionTreeNumericBucketizer": label_col(Real),
    "DecisionTreeNumericMapBucketizer": label_col(RealMap),
    # -- (label, vector) estimators -------------------------------------------
    "SanityChecker": label_vec({"min_variance": 0.0, "max_correlation": 0.999}),
    "LogisticRegression": label_vec(),
    "MultinomialLogisticRegression": label_vec(classes=3),
    "LinearRegression": label_vec(),
    "GeneralizedLinearRegression": label_vec(),
    "LinearSVC": label_vec(),
    "NaiveBayes": label_vec(),
    "MultilayerPerceptronClassifier": label_vec({"max_iter": 20}),
    "RandomForestClassifier": label_vec(_SMALL_TREES),
    "RandomForestRegressor": label_vec(_SMALL_TREES),
    "DecisionTreeClassifier": label_vec({"max_depth": 3}),
    "DecisionTreeRegressor": label_vec({"max_depth": 3}),
    "GradientBoostedTreesClassifier": label_vec(_SMALL_BOOST),
    "GradientBoostedTreesRegressor": label_vec(_SMALL_BOOST),
    "XGBoostClassifier": label_vec(_SMALL_BOOST),
    "XGBoostRegressor": label_vec(_SMALL_BOOST),
}


def _descaler_case(cls):
    ds, (f1, f2) = _typed_ds([("a", Real), ("b", Real)])
    scaler = STAGE_REGISTRY["ScalerTransformer"](
        scaling_type="linear", slope=2.0, intercept=1.0)
    scaled = f1.transform_with(scaler)
    ds = scaler.transform(ds)
    stage = cls()
    scaled.transform_with(stage, scaled)
    return stage, ds, {}


def _drop_indices_case(cls):
    ds, feats = _vector_pair_ds()
    stage = cls(match_fn=_is_present)  # drops nothing (metadata always present)
    feats[0].transform_with(stage)
    return stage, ds, {"check_serde": False, "check_row_parity": False}


CASES["DropIndicesByTransformer"] = _drop_indices_case
CASES["DescalerTransformer"] = _descaler_case


#: estimator case -> fitted-model class it must produce (covers the Model
#: classes whose constructors take fitted state)
EXPECTED_MODEL = {
    "FillMissingWithMean": "FillMissingWithMeanModel",
    "StandardScaler": "StandardScalerModel",
    "PercentileCalibrator": "PercentileCalibratorModel",
    "StringIndexer": "StringIndexerModel",
    "CountVectorizer": "CountVectorizerModel",
    "LDA": "LDAModel",
    "Word2Vec": "Word2VecModel",
    "OneHotVectorizer": "OneHotVectorizerModel",
    "MultiPickListVectorizer": "MultiPickListVectorizerModel",
    "SmartTextVectorizer": "SmartTextVectorizerModel",
    "SmartTextMapVectorizer": "SmartTextMapVectorizerModel",
    "TextMapPivotVectorizer": "TextMapPivotVectorizerModel",
    "NumericVectorizer": "NumericVectorizerModel",
    "NumericMapVectorizer": "NumericMapVectorizerModel",
    "GeolocationVectorizer": "GeolocationVectorizerModel",
    "GeolocationMapVectorizer": "GeolocationMapVectorizerModel",
    "DateMapToUnitCircleVectorizer": "DateMapToUnitCircleVectorizerModel",
    "DecisionTreeNumericBucketizer": "DecisionTreeNumericBucketizerModel",
    "DecisionTreeNumericMapBucketizer": "DecisionTreeNumericMapBucketizerModel",
    "IsotonicRegressionCalibrator": "IsotonicCalibratorModel",
    "SanityChecker": "SanityCheckerModel",
    "LogisticRegression": "LogisticRegressionModel",
    "MultinomialLogisticRegression": "MultinomialLogisticRegressionModel",
    "LinearRegression": "LinearRegressionModel",
    "GeneralizedLinearRegression": "GLMModel",
    "LinearSVC": "LinearSVCModel",
    "NaiveBayes": "NaiveBayesModel",
    "MultilayerPerceptronClassifier": "MLPClassifierModel",
    "RandomForestClassifier": "ForestClassifierModel",
    "RandomForestRegressor": "ForestRegressorModel",
    "DecisionTreeClassifier": "ForestClassifierModel",
    "DecisionTreeRegressor": "ForestRegressorModel",
    "GradientBoostedTreesClassifier": "GBTClassifierModel",
    "GradientBoostedTreesRegressor": "GBTRegressorModel",
    "XGBoostClassifier": "GBTClassifierModel",
    "XGBoostRegressor": "GBTRegressorModel",
}


#: registered classes deliberately NOT swept here, each with a reason
EXEMPT = {
    # abstract arity/framework bases — never instantiated directly
    "Transformer": "abstract base",
    "UnaryTransformer": "abstract base",
    "BinaryTransformer": "abstract base",
    "TernaryTransformer": "abstract base",
    "QuaternaryTransformer": "abstract base",
    "SequenceTransformer": "abstract base",
    "Estimator": "abstract base",
    "UnaryEstimator": "abstract base",
    "BinaryEstimator": "abstract base",
    "TernaryEstimator": "abstract base",
    "SequenceEstimator": "abstract base",
    "BinarySequenceEstimator": "abstract base",
    "PredictionEstimatorBase": "abstract base for model families",
    "PredictionModelBase": "abstract base for fitted models",
    "_LiftBase": "abstract base for LiftToList/LiftToMap",
    "_UnaryValueTransformer": "abstract base for value transformers",
    "_ForestBase": "abstract base for RF/DT",
    "_GBTBase": "abstract base for GBT/XGBoost",
    "_TreeEstimatorBase": "abstract base for tree estimators",
    "_TreeEnsembleModelBase": "abstract base for tree models",
    # constructed through other machinery, spec-covered elsewhere
    "FeatureGeneratorStage":
        "constructed by FeatureBuilder.extract_field; exercised by every "
        "workflow test (tests/test_features_dag.py)",
    "ModelSelector":
        "requires models+validator config; selection behavior covered in "
        "tests/test_models_selector.py and tests/test_workflow_e2e.py",
    "SelectedModel":
        "product of ModelSelector.fit (serde + scoring covered in "
        "tests/test_models_selector.py, tests/test_workflow_e2e.py)",
    "SelectedModelCombiner":
        "requires two upstream Prediction features; covered in "
        "tests/test_combiner.py",
    "SelectedCombinerModel":
        "product of SelectedModelCombiner.fit; covered in tests/test_combiner.py",
    "RecordInsightsLOCO":
        "requires a fitted prediction model arg; covered in tests/test_insights.py",
    "RecordInsightsCorr":
        "requires a fitted prediction model arg; covered in tests/test_insights.py",
}


def test_case_tables_are_disjoint_and_known():
    assert not set(CASES) & set(EXEMPT)
    unknown = (set(CASES) | set(EXEMPT) | set(EXPECTED_MODEL.values())) \
        - set(STAGE_REGISTRY)
    assert not unknown, f"case tables reference unregistered stages: {unknown}"


@pytest.mark.parametrize("name", sorted(CASES))
def test_stage_spec(name):
    cls = STAGE_REGISTRY[name]
    stage, ds, flags = CASES[name](cls)
    if isinstance(stage, Estimator):
        model = assert_estimator_spec(stage, ds, **flags)
        assert type(model).__name__ == EXPECTED_MODEL[name], (
            f"update EXPECTED_MODEL: {name} produced {type(model).__name__}")
    else:
        assert_transformer_spec(stage, ds, **flags)


def test_registry_fully_covered():
    """Every PACKAGE stage is swept, a swept estimator's model product, or
    explicitly exempted with a reason.  Stage classes test modules define for
    their own fixtures register too — those are out of scope."""
    covered = set(CASES) | set(EXPECTED_MODEL.values()) | set(EXEMPT)
    package = {n for n, c in STAGE_REGISTRY.items()
               if c.__module__.startswith("transmogrifai_tpu.")}
    missing = sorted(package - covered)
    assert not missing, (
        f"stages registered without spec coverage or exemption: {missing}")
