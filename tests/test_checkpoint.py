"""Stage-granular checkpoint/resume (SURVEY §5.4 sweep-level resume)."""

import numpy as np

from transmogrifai_tpu import (
    BinaryClassificationModelSelector,
    Dataset,
    FeatureBuilder,
    Workflow,
    transmogrify,
)
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.types import Real, RealNN
from transmogrifai_tpu.utils.listener import (
    OpMetricsListener,
    add_listener,
    remove_listener,
)
from transmogrifai_tpu.workflow.checkpoint import StageCheckpointer


def _pipeline(seed=0):
    rng = np.random.default_rng(seed)
    n = 160
    cols = {f"x{i}": rng.normal(size=n).tolist() for i in range(3)}
    cols["label"] = (rng.random(n) > 0.5).astype(float).tolist()
    ds = Dataset.from_features(
        cols, {**{f"x{i}": Real for i in range(3)}, "label": RealNN})
    label = FeatureBuilder.of("label", RealNN).extract_field().as_response()
    feats = [FeatureBuilder.of(f"x{i}", Real).extract_field().as_predictor()
             for i in range(3)]
    checked = label.sanity_check(transmogrify(feats))
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, models=[(LogisticRegression(), [{"reg_param": 0.01}])])
    pred = label.transform_with(sel, checked)
    return ds, label, pred


class TestStageCheckpointer:
    def test_first_run_writes_stages(self, tmp_path):
        ds, label, pred = _pipeline()
        ckpt = StageCheckpointer(str(tmp_path))
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        wf.train(checkpointer=ckpt)
        loaded = ckpt.load_all()
        assert len(loaded) >= 3  # vectorizer, sanity checker, selector at least
        assert any(type(m).__name__ == "SelectedModel" for m in loaded.values())

    def test_resume_skips_fitting(self, tmp_path):
        ds, label, pred = _pipeline()
        ckpt = StageCheckpointer(str(tmp_path))
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        m1 = wf.train(checkpointer=ckpt)
        s1 = np.asarray(m1.score(ds)[pred.name].score)

        listener = add_listener(OpMetricsListener())
        try:
            m2 = wf.train(checkpointer=ckpt)
        finally:
            remove_listener(listener)
        fits = [m for m in listener.metrics.stage_metrics if m.phase == "fit"]
        assert fits == []  # everything resumed from disk
        s2 = np.asarray(m2.score(ds)[pred.name].score)
        np.testing.assert_allclose(s1, s2, atol=1e-9)

    def test_partial_resume_fits_missing_only(self, tmp_path):
        ds, label, pred = _pipeline()
        ckpt = StageCheckpointer(str(tmp_path))
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        wf.train(checkpointer=ckpt)
        # drop the selector checkpoint -> only it refits
        import os

        sel_uid = pred.origin_stage.uid
        for name in os.listdir(tmp_path):
            if name.startswith(sel_uid):
                os.remove(tmp_path / name)
        listener = add_listener(OpMetricsListener())
        try:
            wf.train(checkpointer=ckpt)
        finally:
            remove_listener(listener)
        fit_classes = [m.stage_class for m in listener.metrics.stage_metrics
                       if m.phase == "fit"]
        assert fit_classes == ["ModelSelector"]

    def test_clear(self, tmp_path):
        ds, label, pred = _pipeline()
        ckpt = StageCheckpointer(str(tmp_path))
        Workflow().set_input_dataset(ds).set_result_features(label, pred).train(
            checkpointer=ckpt)
        ckpt.clear()
        assert ckpt.load_all() == {}


class TestWorkflowCVResume:
    def test_resume_skips_cv_sweep(self, tmp_path):
        """With the selector checkpointed, re-running a with_workflow_cv train
        must not redo the fold sweep (no SanityChecker fold fits)."""
        ds, label, pred = _pipeline()
        ckpt = StageCheckpointer(str(tmp_path))
        wf = (Workflow().set_input_dataset(ds)
              .set_result_features(label, pred).with_workflow_cv())
        wf.train(checkpointer=ckpt)
        listener = add_listener(OpMetricsListener())
        try:
            wf.train(checkpointer=ckpt)
        finally:
            remove_listener(listener)
        fits = [m for m in listener.metrics.stage_metrics if m.phase == "fit"]
        assert fits == []


class TestFingerprint:
    def test_changed_params_refit(self, tmp_path):
        """Re-running with a different grid must NOT reuse the stale selector."""
        ds, label, pred = _pipeline()
        ckpt = StageCheckpointer(str(tmp_path))
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        wf.train(checkpointer=ckpt)
        # mutate a selector-adjacent param on the DAG's SanityChecker
        sc = [s for s in _all_dag_stages(pred)
              if type(s).__name__ == "SanityChecker"][0]
        sc.min_variance = 0.123
        listener = add_listener(OpMetricsListener())
        try:
            wf.train(checkpointer=ckpt)
        finally:
            remove_listener(listener)
        fit_classes = {m.stage_class for m in listener.metrics.stage_metrics
                       if m.phase == "fit"}
        assert "SanityChecker" in fit_classes  # stale checkpoint rejected
        # cascade: the selector consumed the refit checker's output, so its
        # checkpoint is stale too and must also refit
        assert "ModelSelector" in fit_classes


def _all_dag_stages(feature):
    out = []
    seen = set()

    def walk(f):
        st = f.origin_stage
        if st is None or st.uid in seen:
            return
        seen.add(st.uid)
        out.append(st)
        for p in st.inputs:
            walk(p)

    walk(feature)
    return out


class TestAdviceFixes:
    """ADVICE r1: transformer parents must not invalidate downstream checkpoints;
    stale npz removal; lineage fingerprints catch transformer param edits."""

    def _text_pipeline(self, tokenizer_min_len=1):
        from transmogrifai_tpu.ops.text import TextTokenizer
        from transmogrifai_tpu.types import Text, TextList

        rng = np.random.default_rng(3)
        n = 120
        words = ["alpha beta", "gamma delta epsilon", "zeta", "eta theta"]
        cols = {
            "txt": [words[i % 4] for i in range(n)],
            "x0": rng.normal(size=n).tolist(),
            "label": (rng.random(n) > 0.5).astype(float).tolist(),
        }
        ds = Dataset.from_features(
            cols, {"txt": Text, "x0": Real, "label": RealNN})
        label = FeatureBuilder.of("label", RealNN).extract_field().as_response()
        txt = FeatureBuilder.of("txt", Text).extract_field().as_predictor()
        x0 = FeatureBuilder.of("x0", Real).extract_field().as_predictor()
        toks = txt.transform_with(TextTokenizer(min_token_length=tokenizer_min_len))
        vec = transmogrify([toks, x0])
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2, models=[(LogisticRegression(), [{"reg_param": 0.01}])])
        pred = label.transform_with(sel, vec)
        return ds, label, pred

    def test_transformer_parent_does_not_invalidate_resume(self, tmp_path):
        """Estimators downstream of a stateless Transformer (tokenize) must
        resume from checkpoint, not refit (ADVICE r1 medium)."""
        ds, label, pred = self._text_pipeline()
        ckpt = StageCheckpointer(str(tmp_path))
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        wf.train(checkpointer=ckpt)

        listener = add_listener(OpMetricsListener())
        try:
            wf.train(checkpointer=ckpt)
        finally:
            remove_listener(listener)
        fitted = [s.stage_class for s in listener.metrics.stage_metrics
                  if s.phase == "fit"]
        assert fitted == [], f"resume refitted: {fitted}"

    def test_transformer_param_edit_refits_downstream(self, tmp_path):
        """Editing a Transformer param between runs changes the lineage
        fingerprint, so downstream estimator checkpoints refit."""
        ds, label, pred = self._text_pipeline(tokenizer_min_len=1)
        ckpt = StageCheckpointer(str(tmp_path))
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        wf.train(checkpointer=ckpt)

        # second run: same DAG object, tokenizer param changed in place
        from transmogrifai_tpu.workflow.dag import all_stages

        tok = next(s for s in all_stages([label, pred])
                   if type(s).__name__ == "TextTokenizer")
        tok.min_token_length = 3
        listener = add_listener(OpMetricsListener())
        try:
            wf.train(checkpointer=ckpt)
        finally:
            remove_listener(listener)
        fitted = [s.stage_class for s in listener.metrics.stage_metrics
                  if s.phase == "fit"]
        assert fitted != [], "param edit on transformer parent must trigger refits"

    def test_save_stage_removes_stale_npz(self, tmp_path):
        """A refit whose encoding has no arrays must delete a previous npz
        (ADVICE r1 low: otherwise load pairs new json with old arrays)."""
        from transmogrifai_tpu.ops.math import AliasTransformer

        ckpt = StageCheckpointer(str(tmp_path))
        stage = AliasTransformer(name="alias")
        jpath, npath = ckpt._paths(stage.uid)
        # simulate an earlier run that wrote arrays for this uid
        with open(npath, "wb") as fh:
            np.savez(fh, junk=np.zeros(3))
        ckpt.save_stage(stage)
        import os

        assert not os.path.exists(npath)

    def test_refit_cascades_through_intermediate_transformer(self, tmp_path):
        """E1 (estimator) -> Transformer -> E2 (estimator): when E1's checkpoint
        is gone (so E1 refits), E2 must refit too — staleness looks THROUGH the
        transformer to the nearest estimator ancestors."""
        import os

        from transmogrifai_tpu.ops.misc import DropIndicesByTransformer
        from transmogrifai_tpu.workflow.dag import all_stages

        rng = np.random.default_rng(5)
        n = 160
        cols = {f"x{i}": rng.normal(size=n).tolist() for i in range(3)}
        cols["label"] = (rng.random(n) > 0.5).astype(float).tolist()
        ds = Dataset.from_features(
            cols, {**{f"x{i}": Real for i in range(3)}, "label": RealNN})
        label = FeatureBuilder.of("label", RealNN).extract_field().as_response()
        feats = [FeatureBuilder.of(f"x{i}", Real).extract_field().as_predictor()
                 for i in range(3)]
        checked = label.sanity_check(transmogrify(feats))
        passed = checked.transform_with(
            DropIndicesByTransformer(match_fn=_keep_all_slots))
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2, models=[(LogisticRegression(), [{"reg_param": 0.01}])])
        pred = label.transform_with(sel, passed)

        ckpt = StageCheckpointer(str(tmp_path))
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        wf.train(checkpointer=ckpt)

        sanity = next(s for s in all_stages([label, pred])
                      if type(s).__name__ == "SanityChecker")
        for path in ckpt._paths(sanity.uid):
            if os.path.exists(path):
                os.remove(path)

        listener = add_listener(OpMetricsListener())
        try:
            wf.train(checkpointer=ckpt)
        finally:
            remove_listener(listener)
        fitted = [s.stage_class for s in listener.metrics.stage_metrics
                  if s.phase == "fit"]
        assert "SanityChecker" in fitted
        assert any("Selector" in c for c in fitted), (
            f"selector must refit when its (transformer-intermediated) upstream "
            f"estimator refits; fitted={fitted}")


class TestTornCheckpoints:
    """Crash-resume robustness (ISSUE 5 satellite): a truncated/corrupt
    checkpoint file — the torn-write shapes a preempted run leaves behind —
    must log-and-refit that stage, never crash the resumed train()."""

    def _train_once(self, tmp_path):
        ds, label, pred = _pipeline()
        ckpt = StageCheckpointer(str(tmp_path))
        wf = Workflow().set_input_dataset(ds).set_result_features(label, pred)
        wf.train(checkpointer=ckpt)
        return ds, label, pred, ckpt, wf

    def _resume_fits(self, wf, ckpt):
        listener = add_listener(OpMetricsListener())
        try:
            model = wf.train(checkpointer=ckpt)
        finally:
            remove_listener(listener)
        return model, [m.stage_class for m in listener.metrics.stage_metrics
                       if m.phase == "fit"]

    def test_truncated_npz_refits_stage_only(self, tmp_path):
        ds, label, pred, ckpt, wf = self._train_once(tmp_path)
        _jpath, npath = ckpt._paths(pred.origin_stage.uid)
        blob = open(npath, "rb").read()
        with open(npath, "wb") as fh:  # torn write: first half of the zip
            fh.write(blob[:max(1, len(blob) // 2)])
        model, fits = self._resume_fits(wf, ckpt)
        assert fits == ["ModelSelector"], fits  # damaged stage refit, rest resumed
        assert np.isfinite(
            np.asarray(model.score(ds)[pred.name].score)).all()

    def test_corrupt_json_refits_stage_only(self, tmp_path):
        ds, label, pred, ckpt, wf = self._train_once(tmp_path)
        jpath, _npath = ckpt._paths(pred.origin_stage.uid)
        with open(jpath, "w") as fh:
            fh.write('{"className": "SelectedMo')  # torn mid-object
        _model, fits = self._resume_fits(wf, ckpt)
        assert fits == ["ModelSelector"], fits

    def test_json_present_npz_missing_refits(self, tmp_path):
        """json landed, npz lost (the reverse torn-write): decode fails on
        the missing arrays and the stage refits instead of crashing."""
        import os

        ds, label, pred, ckpt, wf = self._train_once(tmp_path)
        _jpath, npath = ckpt._paths(pred.origin_stage.uid)
        if os.path.exists(npath):
            os.remove(npath)
        _model, fits = self._resume_fits(wf, ckpt)
        assert fits == ["ModelSelector"], fits

    def test_load_entries_logs_and_skips(self, tmp_path, caplog):
        import logging

        ds, label, pred, ckpt, wf = self._train_once(tmp_path)
        _jpath, npath = ckpt._paths(pred.origin_stage.uid)
        with open(npath, "wb") as fh:
            fh.write(b"\x00\x01not-a-zip")
        with caplog.at_level(logging.WARNING,
                             logger="transmogrifai_tpu.workflow.checkpoint"):
            loaded = ckpt.load_entries()
        assert pred.origin_stage.uid not in loaded
        assert loaded  # the intact stages still load
        assert any("not loadable" in r.message for r in caplog.records)


def _keep_all_slots(cm):
    return False
