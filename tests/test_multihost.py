"""Pod-scale dp x mp sweep execution (ISSUE 15): sharded fold x grid
programs on the 8-device simulated-CPU mesh, the host-local global-array
assembly path, the TM608/TM609 static scalability gate, and the chunk-tile /
mesh divisibility contract.

CI has no multi-process backend, so verification is the zero-hardware stack:
bitwise sharded-vs-unsharded parity on simulated devices, mocked
``process_index``/``process_count`` arithmetic for the multi-host seams
(the pattern test_distributed.py established), and abstract-trace static
analysis for the scale-out properties no single host can execute.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from transmogrifai_tpu import (
    BinaryClassificationModelSelector,
    Dataset,
    FeatureBuilder,
    Workflow,
    transmogrify,
)
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.models.svm import LinearSVC
from transmogrifai_tpu.parallel import distributed as D
from transmogrifai_tpu.parallel.mesh import (
    constrain,
    constrain_rows,
    make_mesh,
    mesh_token,
    use_mesh,
)
from transmogrifai_tpu.perf import measure_compiles
from transmogrifai_tpu.types import Real, RealNN


def _selector_pipeline(n=211, seed=29, folds=2):
    """LR (IRLS grid) + SVC + GBT: the sharded sweep programs under test."""
    from transmogrifai_tpu.models.trees import GradientBoostedTreesClassifier

    rng = np.random.default_rng(seed)
    cols = {f"x{i}": rng.normal(size=n).tolist() for i in range(4)}
    z = sum((i + 1) * 0.4 * np.asarray(cols[f"x{i}"]) for i in range(4))
    cols["label"] = (rng.random(n) < 1 / (1 + np.exp(-z))
                     ).astype(float).tolist()
    ds = Dataset.from_features(
        cols, {**{f"x{i}": Real for i in range(4)}, "label": RealNN})
    label = FeatureBuilder.of("label", RealNN).extract_field().as_response()
    fs = [FeatureBuilder.of(f"x{i}", Real).extract_field().as_predictor()
          for i in range(4)]
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=folds,
        models=[(LogisticRegression(),
                 [{"reg_param": r} for r in (0.0, 0.01, 0.1)]),
                (LinearSVC(), [{"reg_param": r} for r in (0.01, 0.1)]),
                (GradientBoostedTreesClassifier(num_rounds=3, max_depth=2),
                 [{}])])
    p = label.transform_with(sel, transmogrify(fs))
    return ds, label, p


class TestShardedSweepParity:
    """ACCEPTANCE: sharded-vs-unsharded CV metrics and winner selection
    bitwise-equal on the 4x2 simulated-CPU mesh, and a warm sharded refit
    compiles NOTHING (plan + sweep executable caches keyed on the mesh
    token serve it)."""

    def test_cv_metrics_and_winner_bitwise_and_warm_refit_zero_compiles(self):
        ds, label, p = _selector_pipeline()
        m1 = (Workflow().set_input_dataset(ds)
              .set_result_features(label, p).train())
        with use_mesh(make_mesh(n_data=4, n_model=2)):
            m2 = (Workflow().set_input_dataset(ds)
                  .set_result_features(label, p).train())
            # warm sharded refit: every sweep program, eval program, and
            # fused-prefix executable must come out of the mesh-keyed caches
            with measure_compiles() as probe:
                m3 = (Workflow().set_input_dataset(ds)
                      .set_result_features(label, p).train())
        assert probe.backend_compiles == 0, (
            f"warm sharded refit recompiled {probe.backend_compiles} "
            f"program(s)")

        sm1, sm2, sm3 = m1.summary(), m2.summary(), m3.summary()
        assert sm1.failed_models == [] and sm2.failed_models == []
        ev1 = {(e.model_name, tuple(sorted(e.grid.items()))): e
               for e in sm1.validation_results}
        ev2 = {(e.model_name, tuple(sorted(e.grid.items()))): e
               for e in sm2.validation_results}
        assert set(ev1) == set(ev2)
        for key in ev1:
            v1, v2 = ev1[key].metric_values, ev2[key].metric_values
            assert v1 == v2, (  # bitwise: sharding is layout, never math
                f"CV metrics diverged under the 4x2 mesh for {key}: "
                f"{v1} != {v2}")
        assert sm1.best_model_name == sm2.best_model_name
        assert sm2.best_model_name == sm3.best_model_name

    def test_fused_prefix_runs_sharded_and_bitwise(self):
        """The meshed fused transform prefix must actually execute as ONE
        row-sharded program (it silently fell back to the host path before
        ISSUE 15 — a placed-array indexing bug) and its columns must be
        bitwise-equal to the unmeshed dispatch."""
        from transmogrifai_tpu.workflow.dag import compute_dag
        from transmogrifai_tpu.workflow.plan import plan_for

        ds, label, p = _selector_pipeline(n=150)
        checked = label.sanity_check(
            transmogrify([FeatureBuilder.of(f"x{i}", Real).extract_field()
                          .as_predictor() for i in range(4)]))
        m = (Workflow().set_input_dataset(ds)
             .set_result_features(label, checked).train())
        runners = [m.fitted.get(s.uid, s)
                   for layer in compute_dag(m.result_features)
                   for s in layer]
        plan_u, _ = plan_for(runners, frozenset(ds.names))
        out_u = plan_u.apply_prefix(ds)
        with use_mesh(make_mesh(n_data=4, n_model=2)):
            plan_m, _ = plan_for(runners, frozenset(ds.names))
            # the mesh token keys the plan fingerprint: no aliasing
            assert plan_m.fingerprint != plan_u.fingerprint
            out_m = plan_m.apply_prefix(ds)  # must NOT raise/fall back
        a = np.asarray(out_u[checked.name].data)
        b = np.asarray(out_m[checked.name].data)
        np.testing.assert_array_equal(a, b)


class TestTopologyKeys:
    """Cache keys and plan fingerprints carry the global mesh shape AND the
    process topology, so multi-host executables can never alias
    single-host ones."""

    def test_mesh_token_carries_process_topology(self, monkeypatch):
        with use_mesh(make_mesh(4, 2)):
            t1 = mesh_token()
            monkeypatch.setattr(jax, "process_count", lambda: 4)
            t2 = mesh_token()
        assert t1 != t2 and t1[:2] == t2[:2]
        assert mesh_token() is None  # no ambient mesh -> no token

    def test_run_cached_fingerprint_differs_by_topology(self, monkeypatch):
        from transmogrifai_tpu.models.logistic import _irls_sweep
        from transmogrifai_tpu.perf import cache_key_fingerprint

        args = (np.zeros((64, 5), np.float32), np.zeros(64, np.float32),
                np.zeros((2, 64), np.float32), np.zeros(2, np.float32))
        statics = dict(max_iter=3, has_intercept=True)
        fp_none = cache_key_fingerprint(_irls_sweep, *args, statics=statics)
        with use_mesh(make_mesh(4, 2)):
            fp_mesh = cache_key_fingerprint(_irls_sweep, *args,
                                            statics=statics)
            monkeypatch.setattr(jax, "process_count", lambda: 2)
            fp_pod = cache_key_fingerprint(_irls_sweep, *args,
                                           statics=statics)
        assert len({fp_none, fp_mesh, fp_pod}) == 3

    def test_plan_fingerprint_differs_by_topology(self, monkeypatch):
        from transmogrifai_tpu.ops.numeric import NumericVectorizerModel
        from transmogrifai_tpu.workflow.plan import stage_content_fingerprint

        stage = NumericVectorizerModel(fills=np.array([0.0, 1.0]),
                                       track_nulls=True)
        fp_none = stage_content_fingerprint([stage])
        with use_mesh(make_mesh(4, 2)):
            fp_mesh = stage_content_fingerprint([stage])
            monkeypatch.setattr(jax, "process_count", lambda: 2)
            fp_pod = stage_content_fingerprint([stage])
        assert len({fp_none, fp_mesh, fp_pod}) == 3


class TestGlobalRowAssembly:
    """The host-local ingest seam: each host decodes only its own row span
    and the spans compose to the global array/fit — exercised single-process
    via the mocked process arithmetic (the hardware two-process run stays
    xfail in test_distributed.py)."""

    def test_spans_partition_exactly(self):
        for n, pc in ((10, 3), (8192, 4), (7, 8), (0, 2), (5, 1)):
            spans = D.host_row_spans(n, pc)
            assert len(spans) == pc
            covered = []
            for s in spans:
                covered.extend(range(s.start, s.stop))
            assert covered == list(range(n))

    def test_single_process_assembly_matches_direct_placement(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        with use_mesh(make_mesh(4, 2)) as mesh:
            g = D.global_row_array(x, n_global_rows=64)
            direct = jax.device_put(
                x, jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec("data")))
            np.testing.assert_array_equal(np.asarray(g), np.asarray(direct))

    def test_single_process_partial_block_refused(self):
        x = np.zeros((10, 2), np.float32)
        with use_mesh(make_mesh(4, 2)):
            with pytest.raises(ValueError, match="full 16 rows"):
                D.global_row_array(x[:5], n_global_rows=16)

    def test_mocked_two_host_span_decoding(self, monkeypatch):
        """Under mocked 2-process topology every host's ``host_local_rows``
        slice is its decode contract; the spans must tile the table."""
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        blocks = []
        n = 100
        rng = np.random.default_rng(0)
        x = rng.normal(size=(n, 3)).astype(np.float32)
        for pid in range(2):
            monkeypatch.setattr(jax, "process_index", lambda p=pid: p)
            s = D.host_local_rows(n)
            blocks.append(x[s])
        assert blocks[0].shape == (50, 3) and blocks[1].shape == (50, 3)
        np.testing.assert_array_equal(np.vstack(blocks), x)

    def test_two_simulated_host_contributions_compose_to_global_fit(self):
        """The IRLS/ridge psum math decomposes over host row spans: the
        per-span weighted Gram/moment contributions must sum EXACTLY to the
        single-host statistics (integer-valued fixtures make float addition
        exact), so a two-host fit on span-decoded rows reproduces the
        global fit."""
        n, d = 96, 4
        rng = np.random.default_rng(7)
        x = rng.integers(-3, 4, size=(n, d)).astype(np.float64)
        y = rng.integers(0, 2, size=n).astype(np.float64)
        w = np.ones(n)
        spans = D.host_row_spans(n, 2)
        gram = sum((w[s, None] * x[s]).T @ x[s] for s in spans)
        xty = sum(x[s].T @ (w[s] * y[s]) for s in spans)
        np.testing.assert_array_equal(gram, (w[:, None] * x).T @ x)
        np.testing.assert_array_equal(xty, x.T @ (w * y))
        # and the closed-form fit from composed statistics == global fit
        reg = np.eye(d)
        beta_spans = np.linalg.solve(gram + reg, xty)
        beta_global = np.linalg.solve((w[:, None] * x).T @ x + reg,
                                      x.T @ (w * y))
        np.testing.assert_allclose(beta_spans, beta_global, rtol=1e-12)

    def test_global_mesh_refuses_host_crossing_model_axis(self, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "local_devices", lambda: jax.devices()[:4])
        with pytest.raises(ValueError, match="span hosts"):
            D.global_mesh(n_model=8)
        # non-strict downgrades to a warning
        mesh = D.global_mesh(n_model=8, strict_topology=False)
        assert mesh.shape["model"] == 8

    def test_global_mesh_explicit_devices_checks_process_groups(self):
        """An explicit ``devices`` list is checked off the Device objects'
        own process_index (a per-host count is meaningless there): a model
        group straddling two processes is refused even though the list size
        divides evenly."""
        class _Dev:
            def __init__(self, pidx):
                self.process_index = pidx

        two_hosts = [_Dev(i // 4) for i in range(8)]  # 2 procs x 4 devices
        with pytest.raises(ValueError, match="span hosts"):
            D.global_mesh(n_model=8, devices=two_hosts)
        # groups confined to one process pass the topology check and reach
        # mesh construction (real devices: all one process here)
        mesh = D.global_mesh(n_model=4, devices=jax.devices())
        assert mesh.shape["model"] == 4

    def test_mesh_topology_provenance(self):
        with use_mesh(make_mesh(4, 2)):
            topo = D.mesh_topology()
        assert topo["processCount"] == 1
        assert topo["meshShape"] == {"data": 4, "model": 2}
        assert (topo["dp"], topo["mp"]) == (4, 2)


class TestStaticScalabilityGate:
    """ACCEPTANCE: TM608 fires on a seeded plan whose collective volume
    scales with global rows, stays quiet on the fixed per-host form; TM609
    flags replicated operands over the per-host HBM share."""

    @staticmethod
    def _specs(buckets, d=8):
        return [(b, [jax.ShapeDtypeStruct((b, d), np.float32),
                     jax.ShapeDtypeStruct((d,), np.float32)])
                for b in buckets]

    def test_tm608_fires_on_rows_proportional_collectives(self):
        from transmogrifai_tpu.checkers.plancheck import (
            analyze_program, cost_diagnostics)

        def seeded_bad(x, w):
            # replicated pin on a row-shaped intermediate: a per-step
            # all-gather of the whole row block — the shape that cannot
            # scale past one host
            scores = x @ w                       # (rows,)
            scores = constrain(scores)           # P() -> full all-gather
            return scores.sum()

        def fixed(x, w):
            x = constrain_rows(x)                # rows stay on the data axis
            return (x @ w).sum()                 # psum carries a scalar

        with use_mesh(make_mesh(4, 2)):
            r_bad = analyze_program(seeded_bad, self._specs((1024, 8192)),
                                    label="seeded-bad")
            r_fix = analyze_program(fixed, self._specs((1024, 8192)),
                                    label="fixed")
            codes_bad = {d_.code for d_ in cost_diagnostics(r_bad)}
            codes_fix = {d_.code for d_ in cost_diagnostics(r_fix)}
        assert "TM608" in codes_bad, codes_bad
        assert "TM608" not in codes_fix, codes_fix
        assert r_bad.collective_bytes_per_step > 0
        assert r_fix.buckets[-1].collective_bytes == 0

    def test_tm608_quiet_without_mesh(self):
        from transmogrifai_tpu.checkers.plancheck import (
            analyze_program, cost_diagnostics)

        def prog(x, w):
            return (x @ w).sum()

        r = analyze_program(prog, self._specs((1024, 8192)))
        assert all(d_.code not in ("TM608", "TM609")
                   for d_ in cost_diagnostics(r, hbm_budget=1.0))

    def test_tm609_fires_on_replicated_operands_over_share(self):
        from transmogrifai_tpu.checkers.plancheck import (
            analyze_program, cost_diagnostics)

        baked = jnp.asarray(np.ones((512, 512), np.float32))  # 1 MiB const

        def prog(x, w):
            x = constrain_rows(x)
            return (x[:, :1] * baked.sum()).sum() + (x @ w).sum()

        with use_mesh(make_mesh(4, 2)):
            r = analyze_program(prog, self._specs((1024,)))
            over = cost_diagnostics(r, hbm_budget=1024 * 1024)      # 1 MiB
            under = cost_diagnostics(r, hbm_budget=64 * 1024 * 1024)
        assert "TM609" in {d_.code for d_ in over}
        assert "TM609" not in {d_.code for d_ in under}
        assert r.replicated_bytes >= 512 * 512 * 4

    def test_tm609_sees_consts_baked_inside_jit_wrapped_programs(self):
        """Every real caller hands analyze_program a jit-WRAPPED fn, which
        stages as one pjit eqn binding its consts in the sub-jaxpr — the
        top-level constvars are empty.  The replication evidence must see
        through the wrapper or the gate silently never fires."""
        from transmogrifai_tpu.checkers.plancheck import (
            analyze_program, cost_diagnostics)

        baked = jnp.asarray(np.ones((512, 512), np.float32))  # 1 MiB const

        @jax.jit
        def prog(x, w):
            x = constrain_rows(x)
            return (x[:, :1] * baked.sum()).sum() + (x @ w).sum()

        with use_mesh(make_mesh(4, 2)):
            r = analyze_program(prog, self._specs((1024,)))
            over = cost_diagnostics(r, hbm_budget=1024 * 1024)
        assert r.replicated_bytes >= 512 * 512 * 4
        assert "TM609" in {d_.code for d_ in over}

    def test_sharded_sweep_program_passes_the_gate(self):
        """The REAL sharded IRLS sweep must be per-host clean: collective
        volume flat across the row ladder (no TM608) — the static proof the
        bench ``multihost`` section records."""
        from functools import partial

        from transmogrifai_tpu.checkers.plancheck import (
            analyze_program, cost_diagnostics)
        from transmogrifai_tpu.models.logistic import _irls_sweep

        k, g, d1 = 2, 3, 9

        def specs(b):
            return [jax.ShapeDtypeStruct((b, d1), np.float32),
                    jax.ShapeDtypeStruct((b,), np.float32),
                    jax.ShapeDtypeStruct((k, b), np.float32),
                    jax.ShapeDtypeStruct((g,), np.float32)]

        fn = partial(_irls_sweep, max_iter=3, has_intercept=True)
        with use_mesh(make_mesh(4, 2)):
            r = analyze_program(fn, [(b, specs(b)) for b in (1024, 8192)],
                                label="irls_sweep@4x2")
            codes = {d_.code for d_ in cost_diagnostics(r)}
        assert "TM608" not in codes, codes


class TestChunkTileMeshDivisibility:
    """ISSUE 15 satellite: chunked epochs under ``use_mesh`` keep the chunk
    tile divisible by the data-axis size (computed once per epoch), so chunk
    boundaries compile ZERO new executables on a mesh and the outputs stay
    bitwise-equal to the in-memory dispatch."""

    def test_mesh_aligned_tile(self):
        from transmogrifai_tpu.workflow.plan import mesh_aligned_tile

        assert mesh_aligned_tile(8192) == 8192          # no mesh: unchanged
        with use_mesh(make_mesh(4, 2)):
            assert mesh_aligned_tile(8192) == 8192      # 4 | 8192
            assert mesh_aligned_tile(100) == 128        # pow2 already aligned
        with use_mesh(make_mesh(8, 1)):
            assert mesh_aligned_tile(8192) == 8192

    def test_chunked_epoch_zero_compiles_and_bitwise_under_4x2_mesh(self):
        from transmogrifai_tpu.data.chunked import ChunkedDataset
        from transmogrifai_tpu.workflow.dag import compute_dag
        from transmogrifai_tpu.workflow.fit import transform_dag
        from transmogrifai_tpu.workflow.ooc import chunked_transform_epoch

        rng = np.random.default_rng(17)
        n = 700
        cols = {f"x{i}": rng.normal(size=n).tolist() for i in range(3)}
        cols["label"] = (rng.random(n) < 0.5).astype(float).tolist()
        ds = Dataset.from_features(
            cols, {**{f"x{i}": Real for i in range(3)}, "label": RealNN})
        label = FeatureBuilder.of("label", RealNN).extract_field() \
            .as_response()
        checked = label.sanity_check(transmogrify(
            [FeatureBuilder.of(f"x{i}", Real).extract_field().as_predictor()
             for i in range(3)]))
        m = (Workflow().set_input_dataset(ds)
             .set_result_features(label, checked).train())
        runners = [m.fitted.get(s.uid, s)
                   for layer in compute_dag(m.result_features)
                   for s in layer]

        with use_mesh(make_mesh(n_data=4, n_model=2)):
            in_mem = transform_dag(ds, m.result_features, m.fitted)
            cds = ChunkedDataset.from_dataset(ds, chunk_rows=256)
            out1 = chunked_transform_epoch(cds, runners)
            # chunk boundaries + a full second epoch: zero new executables
            with measure_compiles() as probe:
                out2 = chunked_transform_epoch(cds, runners)
            assert probe.backend_compiles == 0, probe.backend_compiles
        idx = np.arange(n, dtype=np.intp)
        for out in (out1, out2):
            got = np.asarray(out.take(idx)[checked.name].data)
            np.testing.assert_array_equal(
                got, np.asarray(in_mem[checked.name].data))
