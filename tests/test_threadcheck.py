"""TM31x concurrency analyzer (ISSUE 16 tentpole): lockset/guarded-by
inference, lock-order deadlock graph, and blocking-under-lock detection
(checkers/threadcheck.py).

Discipline mirrored from test_plancheck.py: every seeded fixture fires
exactly its own code, every quiet fixture stays silent, and the whole
analysis is pure AST work — the compile probe must read ZERO backend
compiles across a full self-host pass.  The regression tests at the bottom
pin the real races this analyzer surfaced in the serving stack (prefetch
stats accumulators, flight-recorder counter snapshot, fault-harness
schedule edits) as behavioral tests, not just lint assertions.
"""

import os
import threading
import time

import pytest

from transmogrifai_tpu.checkers.threadcheck import (
    analyze_files,
    analyze_source,
    module_global_findings,
)
from transmogrifai_tpu.perf import measure_compiles

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO_ROOT, "transmogrifai_tpu")


def codes(src, filename="fixture.py"):
    return sorted({f.code for f in analyze_source(src, filename).findings})


# ---------------------------------------------------------------------------
# seeded one-shot fixtures: each fires exactly its own code
# ---------------------------------------------------------------------------

TM311_FIXTURE = '''
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        while True:
            with self._lock:
                self._total = self._total + 1

    def snapshot(self):
        return self._total
'''

TM312_FIXTURE = '''
import threading

class Counter:
    def __init__(self):
        self._n = 0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        self._n += 1

    def bump(self):
        self._n += 1
'''

TM313_FIXTURE = '''
import threading

_A_LOCK = threading.Lock()
_B_LOCK = threading.Lock()

def forward():
    with _A_LOCK:
        with _B_LOCK:
            pass

def backward():
    with _B_LOCK:
        with _A_LOCK:
            pass
'''

TM314_FIXTURE = '''
import threading

class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._num = 0.0
        self._den = 1.0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self._num = 1.0
            self._den = 2.0

    def ratio(self):
        return self._num / self._den
'''

TM315_FIXTURE = '''
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            pass

    def stop(self):
        with self._lock:
            self._thread.join()
'''

SEEDED = {
    "TM311": TM311_FIXTURE,
    "TM312": TM312_FIXTURE,
    "TM313": TM313_FIXTURE,
    "TM314": TM314_FIXTURE,
    "TM315": TM315_FIXTURE,
}


@pytest.mark.parametrize("code", sorted(SEEDED))
def test_seeded_fixture_fires_exactly_its_own_code(code):
    assert codes(SEEDED[code]) == [code]


def test_seeded_fixtures_carry_both_sites():
    """TM311/TM314 messages name the guarded counter-site, TM313 the full
    cycle path with per-edge sites, TM315 the held lock."""
    f311 = analyze_source(TM311_FIXTURE, "f.py").findings[0]
    assert "line" in f311.message and "Counter._lock" in f311.message
    f313 = analyze_source(TM313_FIXTURE, "f.py").findings[0]
    assert "f:_A_LOCK" in f313.message and "f:_B_LOCK" in f313.message
    f315 = analyze_source(TM315_FIXTURE, "f.py").findings[0]
    assert "Worker._lock" in f315.message


# ---------------------------------------------------------------------------
# quiet-on-correct-code fixtures: the fixed version of each hazard is silent
# ---------------------------------------------------------------------------

QUIET = {
    # TM311: every access of the shared attr holds the guard
    "TM311": '''
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        while True:
            with self._lock:
                self._total = self._total + 1

    def snapshot(self):
        with self._lock:
            return self._total
''',
    # TM312: the read-modify-write takes a lock on both sides
    "TM312": '''
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self._n += 1

    def bump(self):
        with self._lock:
            self._n += 1
''',
    # TM313: both paths honor one global acquisition order
    "TM313": '''
import threading

_A_LOCK = threading.Lock()
_B_LOCK = threading.Lock()

def forward():
    with _A_LOCK:
        with _B_LOCK:
            pass

def also_forward():
    with _A_LOCK:
        with _B_LOCK:
            pass
''',
    # TM314: the multi-field read snapshots under the writers' lock
    "TM314": '''
import threading

class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._num = 0.0
        self._den = 1.0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self._num = 1.0
            self._den = 2.0

    def ratio(self):
        with self._lock:
            return self._num / self._den
''',
    # TM315: the join happens after the lock is released
    "TM315": '''
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            pass

    def stop(self):
        with self._lock:
            pass
        self._thread.join()
''',
}


@pytest.mark.parametrize("code", sorted(QUIET))
def test_quiet_fixture_is_silent(code):
    assert codes(QUIET[code]) == []


# ---------------------------------------------------------------------------
# analyzer semantics worth pinning individually
# ---------------------------------------------------------------------------

def test_condition_aliasing_no_false_positive():
    """``Condition(self._lock)`` canonicalizes to the underlying lock, so a
    ``with self._cond:`` access site counts as holding ``_lock``."""
    src = '''
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items = []
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._cond:
            self._items.append(1)

    def drain(self):
        with self._lock:
            out = list(self._items)
            self._items.clear()
        return out
'''
    assert codes(src) == []


def test_caller_holds_lock_helper_suffix():
    """A ``*_locked`` helper is analyzed as entered with the primary lock
    held (the repo's documented caller-holds-lock convention)."""
    src = '''
import threading

class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self._n += 1

    def read(self):
        with self._lock:
            return self._n
'''
    assert codes(src) == []


def test_init_construction_happens_before_excluded():
    """Unlocked writes in ``__init__`` AND in private helpers called only
    from it never count: construction happens-before any second thread."""
    src = '''
import threading

class Plan:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = []
        self._build()

    def _build(self):
        self._entries.append(1)
        self._entries.append(2)

    def read(self):
        with self._lock:
            return list(self._entries)

    def grow(self):
        with self._lock:
            self._entries.append(3)
'''
    assert codes(src) == []


def test_declared_concurrent_class_without_own_thread():
    """RacerD's declared-concurrency assumption: a class that constructs its
    own lock is analyzed even with no ``Thread(target=...)`` of its own."""
    src = '''
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        self._n += 1

    def read(self):
        with self._lock:
            return self._n
'''
    assert codes(src) == ["TM312"]


def test_loop_header_reads_are_access_sites():
    """``for x in self._items:`` is a read of the shared list (the gap that
    originally hid the fault-harness ``_rules`` race)."""
    src = '''
import threading

class H:
    def __init__(self):
        self._lock = threading.Lock()
        self._rules = []

    def add(self, r):
        self._rules.append(r)

    def check(self):
        with self._lock:
            for r in self._rules:
                pass
'''
    assert codes(src) == ["TM312"]


def test_lock_order_cycle_across_modules():
    """TM313 edges merge across files: each module alone is cycle-free."""
    fwd = '''
import threading
from locks import A_LOCK, B_LOCK

def forward():
    with A_LOCK:
        with B_LOCK:
            pass
'''
    bwd = '''
import threading
from locks import A_LOCK, B_LOCK

def backward():
    with B_LOCK:
        with A_LOCK:
            pass
'''
    import ast
    from transmogrifai_tpu.checkers.threadcheck import analyze_parsed

    one = analyze_parsed([(fwd, "fwd.py", ast.parse(fwd))])
    assert [f.code for f in one.findings] == []
    both = analyze_parsed([(fwd, "fwd.py", ast.parse(fwd)),
                           (bwd, "bwd.py", ast.parse(bwd))])
    assert sorted({f.code for f in both.findings}) == ["TM313"]


def test_inline_allow_marker_suppresses():
    src = TM312_FIXTURE.replace(
        "self._n += 1\n\n    def bump",
        "self._n += 1  # opcheck: allow(TM312) single-writer by design\n\n"
        "    def bump")
    found = codes(src)
    # only the un-marked bump() site remains
    assert found == ["TM312"]
    all_marked = TM312_FIXTURE.replace(
        "self._n += 1",
        "self._n += 1  # opcheck: allow(TM312) single-writer by design")
    assert codes(all_marked) == []


def test_tm306_delegation_identical_through_both_entry_points():
    """opcheck.lint_module_concurrency is a delegate of the threadcheck
    engine: same findings, same code, same allow-marker handling."""
    from transmogrifai_tpu.checkers.opcheck import lint_module_concurrency

    src = '''
import threading

_CACHE = {}
_LOCK = threading.Lock()

def racy(key, value):
    _CACHE[key] = value

def safe(key, value):
    with _LOCK:
        _CACHE[key] = value
'''
    a = [(f.code, f.qualname, f.lineno) for f in
         lint_module_concurrency(src, filename="m.py")]
    b = [(f.code, f.qualname, f.lineno) for f in
         module_global_findings(src, filename="m.py")]
    assert a == b
    assert [c for c, _q, _l in a] == ["TM306"]


# ---------------------------------------------------------------------------
# self-host: the analyzer over its own serving stack, at zero compiles
# ---------------------------------------------------------------------------

def _threaded_surface_paths():
    paths = []
    for d in ("serve", "obs", "parallel", "perf", os.path.join("perf",
              "kernels"), "checkers"):
        full = os.path.join(PKG, d)
        paths += sorted(os.path.join(full, f) for f in os.listdir(full)
                        if f.endswith(".py"))
    paths += [os.path.join(PKG, "workflow", "continual.py"),
              os.path.join(PKG, "readers", "prefetch.py"),
              os.path.join(PKG, "data", "chunked.py")]
    return paths


def test_self_host_zero_findings_at_zero_compiles():
    """The acceptance gate: the full threaded surface analyzes clean (every
    finding fixed or justified inline) and the probe reads 0 compiles."""
    with measure_compiles() as c:
        analysis = analyze_files(_threaded_surface_paths())
    assert c.backend_compiles == 0
    assert analysis.findings == [], [
        f"{f.code} {f.filename}:{f.lineno} {f.message}"
        for f in analysis.findings]


def test_self_host_thread_model_is_nontrivial():
    """Discovery must actually see the serving stack's structure — a model
    that found nothing would mean the gate gates nothing."""
    model = analyze_files(_threaded_surface_paths()).model.to_dict()
    targets = {t["target"] for t in model["threads"]}
    assert {"MicroBatcher._run", "SwappableScorer._shadow_worker",
            "ChunkPrefetcher._run"} <= targets
    assert {"MicroBatcher", "SwappableScorer",
            "ChunkPrefetcher"} <= set(model["sharedClasses"])
    edges = {tuple(e) for e in model["lockOrderEdges"]}
    assert ("ModelRegistry._admission_lock",
            "ModelRegistry._lock") in edges
    assert len(edges) >= 3


# ---------------------------------------------------------------------------
# regression tests for the races the analyzer surfaced (ISSUE 16 satellite)
# ---------------------------------------------------------------------------

def test_prefetch_stats_concurrent_accumulation_is_exact():
    """TM312 fix: PrefetchStats accumulators are lock-guarded, so no
    increment is lost under worker/consumer contention."""
    from transmogrifai_tpu.readers.prefetch import PrefetchStats

    stats = PrefetchStats()
    N, K = 8, 500

    def worker():
        for _ in range(K):
            stats.add_load(0.001)
            stats.add_wait(0.0005, stalled=True)
            stats.add_chunk()

    threads = [threading.Thread(target=worker) for _ in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = stats.to_dict()
    assert snap["chunks"] == N * K
    assert snap["stalls"] == N * K
    assert snap["load_seconds"] == pytest.approx(N * K * 0.001)
    assert snap["wait_seconds"] == pytest.approx(N * K * 0.0005)


def test_flight_payload_counters_consistent_with_events():
    """TM314 fix: to_payload snapshots dropped/unexpected_compiles under the
    same lock as the event ring, so ``dropped == last seq - len(events)``
    holds in EVERY concurrent snapshot (stale unlocked counter reads used to
    break it)."""
    from transmogrifai_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(capacity=4)
    stop = threading.Event()
    bad = []

    def hammer():
        while not stop.is_set():
            rec.record("tick")

    def snapshot():
        while not stop.is_set():
            p = rec.to_payload()
            if p["events"]:
                want = p["events"][-1]["seq"] - len(p["events"])
                if p["dropped"] != want:
                    bad.append((p["dropped"], want))

    writers = [threading.Thread(target=hammer) for _ in range(3)]
    reader = threading.Thread(target=snapshot)
    for t in writers + [reader]:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in writers + [reader]:
        t.join()
    assert not bad, f"torn payload snapshots: {bad[:5]}"


def test_fault_harness_schedule_edits_race_free():
    """TM312 fix: script()/fail_when() take the harness lock, so schedule
    edits concurrent with firing lose no entries."""
    from transmogrifai_tpu.serve.faults import FaultHarness

    h = FaultHarness()
    N, K = 4, 200

    def scripter(i):
        for k in range(K):
            h.script(f"point-{i}", [None])
            h.fail_when(f"point-{i}", lambda ctx: False, RuntimeError,
                        times=1)

    def firer():
        for _ in range(N * K):
            h._check("point-0", {})

    threads = [threading.Thread(target=scripter, args=(i,))
               for i in range(N)] + [threading.Thread(target=firer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(h._scripts) == N
    for i in range(N):
        assert len(h._scripts[f"point-{i}"]) == K
    assert len(h._rules) == N * K
    assert h.calls["point-0"] == N * K


def test_fixed_race_sites_stay_clean():
    """The five modules this PR de-raced analyze clean individually — a
    revert of any fix re-fires its TM31x code here, next to the fix."""
    fixed = [os.path.join(PKG, "readers", "prefetch.py"),
             os.path.join(PKG, "obs", "flight.py"),
             os.path.join(PKG, "serve", "faults.py"),
             os.path.join(PKG, "serve", "plan.py"),
             os.path.join(PKG, "serve", "registry.py")]
    analysis = analyze_files(fixed)
    assert analysis.findings == [], [
        f"{f.code} {f.filename}:{f.lineno}" for f in analysis.findings]
