"""Avro story tests (VERDICT r2 #5): vendored container codec, reader
integration, CSV<->Avro round trip, and the .avsc-typed CLI generator.

Reference: AvroReaders.scala:1-134, cli/.../gen/AvroField.scala.
"""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

from transmogrifai_tpu.readers.avro import (
    AvroError,
    dataframe_to_avro,
    ftype_schema_from_avsc,
    parse_schema,
    read_container,
    schema_for_dataframe,
    write_container,
)
from transmogrifai_tpu.readers.files import DataReaders

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = {
    "type": "record", "name": "Person", "fields": [
        {"name": "name", "type": "string"},
        {"name": "age", "type": ["null", "long"]},
        {"name": "score", "type": "double"},
        {"name": "flag", "type": "boolean"},
        {"name": "blob", "type": "bytes"},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "attrs", "type": {"type": "map", "values": "long"}},
        {"name": "kind",
         "type": {"type": "enum", "name": "Kind", "symbols": ["A", "B"]}},
        {"name": "fp",
         "type": {"type": "fixed", "name": "FP", "size": 4}},
    ]}


def _records(n=257):
    return [{"name": f"p{i}", "age": None if i % 3 == 0 else i,
             "score": i * 1.5, "flag": i % 2 == 0, "blob": bytes([i % 256]),
             "tags": [f"t{i}", "x"] if i % 5 else [],
             "attrs": {"k": i, "j": -i} if i % 4 else {},
             "kind": "A" if i % 2 == 0 else "B",
             "fp": bytes([i % 256] * 4)} for i in range(n)]


class TestContainerCodec:
    @pytest.mark.parametrize("codec", ["null", "deflate"])
    def test_round_trip_all_types(self, tmp_path, codec):
        p = str(tmp_path / "t.avro")
        recs = _records()
        n = write_container(p, SCHEMA, iter(recs), codec=codec,
                            block_records=100)  # force multiple blocks
        assert n == len(recs)
        schema, it = read_container(p)
        assert schema["name"] == "Person"
        assert list(it) == recs

    def test_deflate_compresses(self, tmp_path):
        pn, pd_ = str(tmp_path / "n.avro"), str(tmp_path / "d.avro")
        write_container(pn, SCHEMA, iter(_records()), codec="null")
        write_container(pd_, SCHEMA, iter(_records()), codec="deflate")
        assert os.path.getsize(pd_) < os.path.getsize(pn)

    def test_negative_and_large_longs(self, tmp_path):
        schema = {"type": "record", "name": "L",
                  "fields": [{"name": "v", "type": "long"}]}
        vals = [0, -1, 1, 63, -64, 64, 2**40, -(2**40), 2**62, -(2**62)]
        p = str(tmp_path / "l.avro")
        write_container(p, schema, ({"v": v} for v in vals))
        _, it = read_container(p)
        assert [r["v"] for r in it] == vals

    def test_not_avro_rejected(self, tmp_path):
        p = str(tmp_path / "x.avro")
        with open(p, "wb") as fh:
            fh.write(b"not an avro file at all")
        with pytest.raises(AvroError):
            read_container(p)

    def test_corrupt_sync_rejected(self, tmp_path):
        p = str(tmp_path / "c.avro")
        write_container(p, SCHEMA, iter(_records(50)), codec="null")
        data = bytearray(open(p, "rb").read())
        data[-3] ^= 0xFF  # flip a bit inside the trailing sync marker
        open(p, "wb").write(bytes(data))
        _, it = read_container(p)
        with pytest.raises(AvroError):
            list(it)

    def test_unknown_codec_rejected(self, tmp_path):
        with pytest.raises(AvroError):
            write_container(str(tmp_path / "z.avro"), SCHEMA, [],
                            codec="snappy")

    def test_bad_schema_rejected(self):
        with pytest.raises(AvroError):
            parse_schema('{"type": "wibble"}')

    def test_nullable_named_type_round_trip(self, tmp_path):
        """Unions referencing NAMED types (["null", "SomeRecord"]) must
        encode: the branch matcher resolves names (r3 advisor finding)."""
        schema = {
            "type": "record", "name": "Outer", "fields": [
                {"name": "addr", "type": [
                    "null",
                    {"type": "record", "name": "Addr", "fields": [
                        {"name": "city", "type": "string"},
                        {"name": "zip", "type": "long"}]}]},
                # second field refers to Addr BY NAME inside a union
                {"name": "alt", "type": ["null", "Addr"]},
                {"name": "kind", "type": [
                    "null",
                    {"type": "enum", "name": "K", "symbols": ["X", "Y"]}]},
                {"name": "kind2", "type": ["null", "K"]},
                {"name": "fp", "type": [
                    "null", {"type": "fixed", "name": "F", "size": 2}]},
                {"name": "fp2", "type": ["null", "F"]},
            ]}
        recs = [
            {"addr": {"city": "sf", "zip": 94105}, "alt": None,
             "kind": "X", "kind2": None, "fp": b"ab", "fp2": None},
            {"addr": None, "alt": {"city": "nyc", "zip": 10001},
             "kind": None, "kind2": "Y", "fp": None, "fp2": b"cd"},
        ]
        p = str(tmp_path / "named.avro")
        assert write_container(p, schema, iter(recs)) == 2
        _, it = read_container(p)
        assert list(it) == recs


class TestCsvAvroRoundTrip:
    def test_csv_to_avro_to_reader(self, tmp_path):
        """CSV -> Avro conversion -> DataReaders.Simple.avro returns the
        same records (the reference csvToAvro + AvroReaders path)."""
        rng = np.random.default_rng(0)
        df = pd.DataFrame({
            "label": rng.integers(0, 2, 40).astype(float),
            "x": rng.normal(size=40),
            "c": rng.choice(["a", "b", None], 40),
            "k": rng.integers(0, 100, 40),
        })
        csv = str(tmp_path / "d.csv")
        df.to_csv(csv, index=False)
        avro = str(tmp_path / "d.avro")
        n = dataframe_to_avro(pd.read_csv(csv), avro)
        assert n == 40

        reader = DataReaders.Simple.avro(avro)
        recs = list(reader.read_records())
        assert len(recs) == 40
        df2 = pd.read_csv(csv)
        for i in (0, 7, 39):
            assert recs[i]["k"] == int(df2["k"][i])
            np.testing.assert_allclose(recs[i]["x"], df2["x"][i])
            c = df2["c"][i]
            assert recs[i]["c"] == (None if pd.isna(c) else c)
        assert reader.schema["fields"][0]["name"] == "label"

    def test_schema_for_dataframe_types(self):
        df = pd.DataFrame({"i": [1], "f": [1.5], "b": [True], "s": ["x"]})
        s = schema_for_dataframe(df)
        types = {f["name"]: f["type"][1] for f in s["fields"]}
        assert types == {"i": "long", "f": "double", "b": "boolean",
                         "s": "string"}


class TestAvscCli:
    AVSC = """{
      "type": "record", "name": "Passenger", "fields": [
        {"name": "id", "type": "string"},
        {"name": "label", "type": "double"},
        {"name": "x", "type": ["null", "double"]},
        {"name": "c", "type": ["null", "string"]}
      ]
    }"""

    def _data(self, n=150, seed=3):
        rng = np.random.default_rng(seed)
        x = rng.normal(0, 1, n)
        c = rng.choice(["a", "b"], n)
        y = (rng.random(n) < 1 / (1 + np.exp(-(2 * x + (c == "a"))))
             ).astype(float)
        return pd.DataFrame({"id": [f"r{i}" for i in range(n)],
                             "label": y, "x": x, "c": c})

    def test_ftype_mapping(self):
        schema = ftype_schema_from_avsc(self.AVSC, id_column="id")
        assert schema == {"id": "ID", "label": "Real", "x": "Real",
                          "c": "Text"}

    def test_gen_from_avsc_produces_typed_project(self, tmp_path):
        from transmogrifai_tpu.cli import generate_project

        df = self._data()
        csv = str(tmp_path / "d.csv")
        df.to_csv(csv, index=False)
        avsc = str(tmp_path / "d.avsc")
        with open(avsc, "w") as fh:
            fh.write(self.AVSC)
        out, kind = generate_project(csv, "label", str(tmp_path / "proj"),
                                     name="avsc-app", id_column="id",
                                     schema_path=avsc)
        assert kind.value == "binary"
        main_py = open(os.path.join(out, "main.py")).read()
        # types came from the .avsc (x typed Real via the union), not inference
        assert '"x": "Real"' in main_py
        assert '"id": "ID"' in main_py

    @pytest.mark.slow  # full generated-project train; Avro reading is
    # covered by the reader tests above, CLI train by test_runner_cli
    def test_gen_from_avro_input_trains(self, tmp_path):
        """gen --input data.avro: the generated project reads Avro through
        DataReaders.Simple.avro and trains end-to-end."""
        from transmogrifai_tpu.cli import generate_project

        df = self._data()
        avro = str(tmp_path / "data.avro")
        dataframe_to_avro(df.drop(columns=["id"]), avro)
        out, kind = generate_project(avro, "label", str(tmp_path / "proj"),
                                     name="avro-app")
        main_py = open(os.path.join(out, "main.py")).read()
        assert "DataReaders.Simple.avro(DATA)" in main_py
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "main.py", "--run-type", "train",
             "--model-location", str(tmp_path / "m"),
             "--metrics-location", str(tmp_path / "metrics.json")],
            cwd=out, env=env, capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stderr[-2000:]
        assert os.path.exists(str(tmp_path / "metrics.json"))

    def test_avsc_missing_field_rejected(self, tmp_path):
        from transmogrifai_tpu.cli import generate_project

        df = self._data().drop(columns=["c"])
        csv = str(tmp_path / "d.csv")
        df.to_csv(csv, index=False)
        avsc = str(tmp_path / "d.avsc")
        with open(avsc, "w") as fh:
            fh.write(self.AVSC)
        with pytest.raises(ValueError, match="absent"):
            generate_project(csv, "label", str(tmp_path / "p"),
                             schema_path=avsc)


class TestHeaderOnlySchema:
    def test_read_schema_no_data_scan(self, tmp_path):
        from transmogrifai_tpu.readers.avro import read_schema

        p = str(tmp_path / "t.avro")
        write_container(p, SCHEMA, iter(_records(500)))
        s = read_schema(p)
        assert s["name"] == "Person"

    def test_truncated_varint_raises_avro_error(self, tmp_path):
        p = str(tmp_path / "t.avro")
        write_container(p, SCHEMA, iter(_records(50)), codec="null")
        data = open(p, "rb").read()
        # cut mid-block so a varint or payload ends early
        open(p, "wb").write(data[:len(data) - 37])
        _, it = read_container(p)
        with pytest.raises(AvroError):
            list(it)
