"""Full-surface standalone export (VERDICT r4 #2): every default serving
shape the framework trains must export to the numpy-only bundle and
round-trip ``score_function`` within 1e-6 in a no-JAX subprocess.

Covers: every transmogrify() default vectorizer family (numeric, binary,
one-hot, multi-hot, smart text categorical + hashed (en + analyzed es),
date unit-circle, date-list pivots, text-list hashing, geolocation, numeric
maps, text-map pivots), string indexer, scalers, and ALL model heads
(logistic/linear/SVC/softmax/NB/MLP/GLM/trees binary+multiclass+regression,
isotonic calibration).  Reference: OpWorkflowModelLocal.scala:93-200 (MLeap
serves any fitted pipeline).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from transmogrifai_tpu import (Dataset, FeatureBuilder, Workflow,
                               transmogrify)
from transmogrifai_tpu.local import export_standalone, score_function
from transmogrifai_tpu.types import (Binary, Date, DateList, Geolocation,
                                     MultiPickList, PickList, Real, RealMap,
                                     RealNN, Text, TextList, TextMap)

_DAY = 86_400_000


def _run_bundle(model, records, out_dir):
    """Export + score in a clean subprocess; returns the scorer's rows."""
    export_standalone(model, str(out_dir))
    driver = (
        "import json, sys\n"
        "sys.path.insert(0, '.')\n"
        "from scorer import Scorer\n"
        "records = json.load(open('records.json'))\n"
        "out = Scorer().score(records)\n"
        "assert 'jax' not in sys.modules\n"
        "assert not any(m.startswith('transmogrifai') "
        "for m in sys.modules)\n"
        "json.dump(out, open('out.json', 'w'))\n")
    with open(os.path.join(str(out_dir), "records.json"), "w") as fh:
        json.dump(records, fh)
    env = {k: v for k, v in os.environ.items() if k not in ("PYTHONPATH",)}
    r = subprocess.run([sys.executable, "-c", driver], cwd=str(out_dir),
                       env=env, capture_output=True, timeout=240)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    return json.load(open(os.path.join(str(out_dir), "out.json")))


def _ref_rows(model, records):
    """In-process reference predictions via score_function."""
    out = []
    for row in score_function(model).batch(records):
        pmaps = [v for v in row.values() if isinstance(v, dict)]
        if pmaps:
            out.append(pmaps[0])
        else:  # scalar output (isotonic calibration)
            out.append({"prediction": next(iter(row.values()))})
    return out


def _assert_probs_match(got, ref, n_classes=2):
    got_p = np.array([row["probability"] for row in got])
    ref_p = np.array([[r[f"probability_{j}"] for j in range(n_classes)]
                      for r in ref])
    np.testing.assert_allclose(got_p, ref_p, atol=1e-6)


def _assert_preds_match(got, ref):
    np.testing.assert_allclose([row["prediction"] for row in got],
                               [r["prediction"] for r in ref], atol=1e-6)


class TestKitchenSinkBinary:
    """Every transmogrify default vectorizer in ONE pipeline -> LR head."""

    N = 400

    def _data(self):
        rng = np.random.default_rng(11)
        n = self.N
        es_words = ["corriendo", "gatos", "casas", "rapidamente", "jugando",
                    "libros", "ciudades", "hablando", "comiendo", "perros"]
        en_words = ["running", "cats", "houses", "quickly", "playing",
                    "books", "cities", "talking", "eating", "dogs"]
        cols = {
            "x1": rng.normal(size=n).tolist(),
            "flag": [bool(v) for v in rng.random(n) < 0.5],
            "color": rng.choice(["red", "green", "blue"], n).tolist(),
            "tags": [sorted(rng.choice(["wifi", "pool", "gym"],
                                       rng.integers(0, 3), replace=False)
                            .tolist()) for _ in range(n)],
            "signup": (1_500_000_000_000
                       + rng.integers(0, 3650, n) * _DAY).tolist(),
            "visits": [sorted((1_500_000_000_000
                               + rng.integers(0, 3650, rng.integers(0, 4))
                               * _DAY).tolist()) for _ in range(n)],
            "loc": [[float(37 + rng.normal()), float(-122 + rng.normal()),
                     5.0] for _ in range(n)],
            # high-cardinality English text -> hashed branch
            "bio": [" ".join(rng.choice(en_words, 6)) for _ in range(n)],
            # high-cardinality Spanish text -> analyzed (stemmed) branch
            "bio_es": [" ".join(rng.choice(es_words, 6)) for _ in range(n)],
            "notes": [rng.choice(en_words, 3).tolist() for _ in range(n)],
            "metrics": [{"a": float(rng.normal()), "b": float(rng.normal())}
                        for _ in range(n)],
            "attrs": [{"plan": str(rng.choice(["basic", "pro"]))}
                      for _ in range(n)],
        }
        label = ((np.asarray(cols["x1"]) > 0)
                 ^ (rng.random(n) < 0.1)).astype(float)
        cols["label"] = label.tolist()
        ftypes = {"x1": Real, "flag": Binary, "color": PickList,
                  "tags": MultiPickList, "signup": Date, "visits": DateList,
                  "loc": Geolocation, "bio": Text, "bio_es": Text,
                  "notes": TextList, "metrics": RealMap, "attrs": TextMap,
                  "label": RealNN}
        return cols, ftypes

    def _train(self):
        from transmogrifai_tpu.models import BinaryClassificationModelSelector
        from transmogrifai_tpu.models.logistic import LogisticRegression

        cols, ftypes = self._data()
        ds = Dataset.from_features(cols, ftypes)
        lab = FeatureBuilder.of("label", RealNN).extract_field().as_response()
        feats = [FeatureBuilder.of(name, ft).extract_field().as_predictor()
                 for name, ft in ftypes.items() if name != "label"]
        checked = lab.sanity_check(transmogrify(feats))
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2,
            models=[(LogisticRegression(), [{"reg_param": 0.01}])])
        pred = lab.transform_with(sel, checked)
        return Workflow().set_input_dataset(ds) \
            .set_result_features(lab, pred).train()

    def test_round_trips(self, tmp_path):
        model = self._train()
        rng = np.random.default_rng(12)
        records = []
        for i in range(48):
            records.append({
                "x1": float(rng.normal()),
                "flag": bool(rng.random() < 0.5),
                "color": str(rng.choice(["red", "green", "violet"])),
                "tags": ["wifi"] if rng.random() < 0.5 else [],
                "signup": int(1_500_000_000_000
                              + int(rng.integers(0, 3650)) * _DAY),
                "visits": [int(1_500_000_000_000 + 3 * _DAY)]
                if rng.random() < 0.7 else [],
                "loc": [37.5, -122.3, 4.0],
                "bio": "cats running quickly",
                "bio_es": "gatos corriendo rapidamente",
                "notes": ["books", "cities"],
                "metrics": {"a": float(rng.normal())},
                "attrs": {"plan": "pro"},
            })
        # missing-value paths
        records[0]["x1"] = None
        records[1]["color"] = None
        records[2]["loc"] = None
        records[3]["signup"] = None
        records[4]["bio"] = None
        records[5]["metrics"] = {}
        records[6]["attrs"] = {}
        got = _run_bundle(model, records, tmp_path / "sink")
        ref = _ref_rows(model, records)
        _assert_probs_match(got, ref)


def _numeric_multiclass_data(seed=21, n=450, n_classes=3):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4))
    y = (x[:, 0] + 0.5 * x[:, 1] > 0.5).astype(int) \
        + (x[:, 2] - x[:, 3] > 0.3).astype(int)
    names = ["setosa", "versicolor", "virginica"]
    cols = {f"x{j}": x[:, j].tolist() for j in range(4)}
    cols["species"] = [names[v] for v in y]
    ftypes = {f"x{j}": RealNN for j in range(4)}
    ftypes["species"] = Text
    return cols, ftypes


def _train_head(head, tmp_path_unused=None):
    """Multiclass pipeline: StringIndexer response + the given head."""
    from transmogrifai_tpu.models.mlp import MultilayerPerceptronClassifier
    from transmogrifai_tpu.models.naive_bayes import NaiveBayes
    from transmogrifai_tpu.models.softmax import MultinomialLogisticRegression
    from transmogrifai_tpu.models.trees import (
        GradientBoostedTreesClassifier, RandomForestClassifier)
    from transmogrifai_tpu.ops.onehot import StringIndexer

    cols, ftypes = _numeric_multiclass_data()
    ds = Dataset.from_features(cols, ftypes)
    species = FeatureBuilder.of("species", Text).extract_field() \
        .as_response()
    label = species.transform_with(StringIndexer(handle_invalid="keep"))
    feats = [FeatureBuilder.of(f"x{j}", RealNN).extract_field()
             .as_predictor() for j in range(4)]
    vec = transmogrify(feats)
    est = {"softmax": lambda: MultinomialLogisticRegression(max_iter=40),
           "nb": lambda: NaiveBayes(),
           "mlp": lambda: MultilayerPerceptronClassifier(
               hidden_layers=(8,), max_iter=60),
           "rf": lambda: RandomForestClassifier(num_trees=10, max_depth=4),
           "gbt": lambda: GradientBoostedTreesClassifier(
               num_rounds=8, max_depth=3)}[head]()
    pred = label.transform_with(est, vec)
    return Workflow().set_input_dataset(ds) \
        .set_result_features(label, pred).train()


class TestMulticlassHeads:
    @pytest.mark.parametrize("head", ["softmax", "nb", "mlp", "rf", "gbt"])
    def test_head_round_trips(self, head, tmp_path):
        model = _train_head(head)
        rng = np.random.default_rng(31)
        records = [{f"x{j}": float(rng.normal()) for j in range(4)}
                   for _ in range(40)]
        got = _run_bundle(model, records, tmp_path / head)
        ref = _ref_rows(model, records)
        _assert_probs_match(got, ref, n_classes=3)
        _assert_preds_match(got, ref)


class TestRegressionHeads:
    @pytest.mark.parametrize("head", ["linear", "glm_gaussian", "glm_poisson",
                                      "gbt_reg", "rf_reg"])
    def test_head_round_trips(self, head, tmp_path):
        from transmogrifai_tpu.models.glm import GeneralizedLinearRegression
        from transmogrifai_tpu.models.linear import LinearRegression
        from transmogrifai_tpu.models.trees import (
            GradientBoostedTreesRegressor, RandomForestRegressor)

        rng = np.random.default_rng(41)
        n = 400
        x = rng.normal(size=(n, 3))
        y = np.exp(0.3 * x[:, 0]) + x[:, 1] ** 2 + rng.normal(scale=0.1,
                                                              size=n)
        cols = {f"x{j}": x[:, j].tolist() for j in range(3)}
        cols["y"] = y.tolist()
        ftypes = {f"x{j}": RealNN for j in range(3)}
        ftypes["y"] = RealNN
        ds = Dataset.from_features(cols, ftypes)
        lab = FeatureBuilder.of("y", RealNN).extract_field().as_response()
        feats = [FeatureBuilder.of(f"x{j}", RealNN).extract_field()
                 .as_predictor() for j in range(3)]
        vec = transmogrify(feats)
        est = {"linear": lambda: LinearRegression(reg_param=0.01),
               "glm_gaussian": lambda: GeneralizedLinearRegression(
                   family="gaussian"),
               "glm_poisson": lambda: GeneralizedLinearRegression(
                   family="poisson"),
               "gbt_reg": lambda: GradientBoostedTreesRegressor(
                   num_rounds=8, max_depth=3),
               "rf_reg": lambda: RandomForestRegressor(
                   num_trees=10, max_depth=4)}[head]()
        pred = lab.transform_with(est, vec)
        model = Workflow().set_input_dataset(ds) \
            .set_result_features(lab, pred).train()
        records = [{f"x{j}": float(rng.normal()) for j in range(3)}
                   for _ in range(40)]
        got = _run_bundle(model, records, tmp_path / head)
        ref = _ref_rows(model, records)
        _assert_preds_match(got, ref)


class TestScalersIndexerIsotonic:
    def test_scaler_pipeline_round_trips(self, tmp_path):
        from transmogrifai_tpu.models.logistic import LogisticRegression
        from transmogrifai_tpu.ops.scalers import StandardScaler

        rng = np.random.default_rng(51)
        n = 300
        x = rng.normal(loc=5.0, scale=2.0, size=n)
        y = ((x > 5) ^ (rng.random(n) < 0.1)).astype(float)
        ds = Dataset.from_features({"x": x.tolist(), "label": y.tolist()},
                                   {"x": RealNN, "label": RealNN})
        lab = FeatureBuilder.of("label", RealNN).extract_field().as_response()
        xf = FeatureBuilder.of("x", RealNN).extract_field().as_predictor()
        scaled = xf.transform_with(StandardScaler())
        vec = transmogrify([scaled])
        pred = lab.transform_with(LogisticRegression(reg_param=0.01), vec)
        model = Workflow().set_input_dataset(ds) \
            .set_result_features(lab, pred).train()
        records = [{"x": float(rng.normal(loc=5.0, scale=2.0))}
                   for _ in range(32)]
        got = _run_bundle(model, records, tmp_path / "scaler")
        ref = _ref_rows(model, records)
        _assert_probs_match(got, ref)

    def test_isotonic_round_trips(self, tmp_path):
        from transmogrifai_tpu.models.isotonic import \
            IsotonicRegressionCalibrator

        rng = np.random.default_rng(61)
        n = 400
        score = rng.uniform(0, 1, n)
        y = (rng.random(n) < score ** 2).astype(float)
        ds = Dataset.from_features(
            {"label": y.tolist(), "score": score.tolist()},
            {"label": RealNN, "score": RealNN})
        lab = FeatureBuilder.of("label", RealNN).extract_field().as_response()
        sc = FeatureBuilder.of("score", RealNN).extract_field().as_predictor()
        cal = lab.transform_with(IsotonicRegressionCalibrator(), sc)
        model = Workflow().set_input_dataset(ds) \
            .set_result_features(lab, cal).train()
        records = [{"score": float(v)} for v in rng.uniform(0, 1, 32)]
        got = _run_bundle(model, records, tmp_path / "iso")
        ref = _ref_rows(model, records)
        _assert_preds_match(got, ref)

    def test_realnn_missing_raises_in_bundle(self, tmp_path):
        """r4 advisor: non-nullable inputs must RAISE at serving, matching
        the in-process NonNullableEmptyException — never impute 0."""
        from transmogrifai_tpu.models.logistic import LogisticRegression

        rng = np.random.default_rng(71)
        n = 200
        x = rng.normal(size=n)
        y = (x > 0).astype(float)
        ds = Dataset.from_features({"x": x.tolist(), "label": y.tolist()},
                                   {"x": RealNN, "label": RealNN})
        lab = FeatureBuilder.of("label", RealNN).extract_field().as_response()
        xf = FeatureBuilder.of("x", RealNN).extract_field().as_predictor()
        vec = transmogrify([xf])
        pred = lab.transform_with(LogisticRegression(), vec)
        model = Workflow().set_input_dataset(ds) \
            .set_result_features(lab, pred).train()
        out_dir = tmp_path / "nn"
        export_standalone(model, str(out_dir))
        driver = (
            "import json, sys\n"
            "sys.path.insert(0, '.')\n"
            "from scorer import Scorer\n"
            "try:\n"
            "    Scorer().score([{'x': None}])\n"
            "except ValueError as e:\n"
            "    assert 'non-nullable' in str(e), str(e)\n"
            "    print('RAISED-OK')\n")
        env = {k: v for k, v in os.environ.items()
               if k not in ("PYTHONPATH",)}
        r = subprocess.run([sys.executable, "-c", driver], cwd=str(out_dir),
                           env=env, capture_output=True, timeout=120)
        assert r.returncode == 0, r.stderr.decode()[-2000:]
        assert b"RAISED-OK" in r.stdout
