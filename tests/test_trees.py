"""Tree-ensemble tests: exact split math, missing-value routing, regularization,
sharded-parity, and workflow/serde integration (reference test strategy SURVEY §4 —
OpEstimatorSpec behavior: fit → model → transform parity → serde round-trip)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from transmogrifai_tpu.data.dataset import Column
from transmogrifai_tpu.models.trees import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    GradientBoostedTreesClassifier,
    GradientBoostedTreesRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
    XGBoostClassifier,
    quantile_bin,
)


def _logloss(p, y):
    p = np.clip(p, 1e-9, 1 - 1e-9)
    return -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()


class TestQuantileBin:
    def test_bins_cover_range_and_missing(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(500, 3)).astype(np.float32)
        x[::7, 1] = np.nan
        binned, edges = quantile_bin(x, n_bins=16)
        assert binned.shape == (500, 3)
        assert edges.shape == (3, 15)
        assert (binned[::7, 1] == 16).all()          # missing -> reserved bin
        ok = ~np.isnan(x)
        assert binned[ok].max() < 16 and binned[ok].min() >= 0
        # monotone: larger value -> same or larger bin
        order = np.argsort(x[:, 0])
        assert (np.diff(binned[order, 0]) >= 0).all()

    def test_constant_column(self):
        x = np.ones((50, 1), dtype=np.float32)
        binned, _ = quantile_bin(x, n_bins=8)
        assert len(np.unique(binned)) == 1


class TestExactTreeMath:
    def test_single_split_leaf_values(self):
        """Hand-computed XGBoost math: depth-1 regression tree, lambda=0, eta=1."""
        x = np.array([[1.0], [2.0], [10.0], [11.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0], dtype=np.float32)
        est = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=0.0,
            min_child_weight=0.0, n_bins=4)
        m = est._fit_arrays(x, y, np.ones(4, dtype=np.float32))
        # base = 0.5; grads = 0.5-y; leaf values -G/H = ±0.5 -> exact predictions
        pred = m.predict_column(Column.vector(x)).pred
        np.testing.assert_allclose(pred, y, atol=1e-6)

    def test_lambda_shrinks_leaves(self):
        x = np.array([[1.0], [2.0], [10.0], [11.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0], dtype=np.float32)
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=2.0,
            min_child_weight=0.0, n_bins=4,
        )._fit_arrays(x, y, np.ones(4, dtype=np.float32))
        pred = m.predict_column(Column.vector(x)).pred
        # leaf value = -G/(H+2) = ±0.25 -> predictions pulled toward base 0.5
        np.testing.assert_allclose(pred, [0.25, 0.25, 0.75, 0.75], atol=1e-6)

    def test_gamma_prunes_to_stump(self):
        x = np.array([[1.0], [2.0], [10.0], [11.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0], dtype=np.float32)
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=3, eta=1.0, reg_lambda=0.0, gamma=1e6,
            n_bins=4)._fit_arrays(x, y, np.ones(4, dtype=np.float32))
        pred = m.predict_column(Column.vector(x)).pred
        np.testing.assert_allclose(pred, 0.5, atol=1e-6)  # no split: base score

    def test_sample_weights_shift_split(self):
        """Zero-weight rows must not influence fitting at all."""
        x = np.array([[1.0], [2.0], [10.0], [11.0], [100.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0, 5.0], dtype=np.float32)
        w = np.array([1, 1, 1, 1, 0], dtype=np.float32)
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=0.0,
            min_child_weight=0.0, n_bins=8)._fit_arrays(x, y, w)
        pred = m.predict_column(Column.vector(x[:4])).pred
        np.testing.assert_allclose(pred, y[:4], atol=1e-6)


class TestMissingValues:
    def test_learned_default_direction(self):
        """Missing values correlated with the positive class must route there."""
        rng = np.random.default_rng(1)
        n = 1000
        x = rng.normal(size=(n, 2)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        miss = rng.random(n) < 0.3
        # make x0 missing mostly on the POSITIVE side
        miss &= y == 1
        x[miss, 0] = np.nan
        m = GradientBoostedTreesClassifier(
            num_rounds=10, max_depth=3, eta=0.5)._fit_arrays(
            x, y, np.ones(n, dtype=np.float32))
        score = m.predict_column(Column.vector(x)).score
        assert score[miss].mean() > 0.7  # missing rows recognized as positive


class TestEnsembles:
    @pytest.fixture(scope="class")
    def binary_data(self):
        rng = np.random.default_rng(2)
        n, d = 1500, 6
        x = rng.normal(size=(n, d)).astype(np.float32)
        logit = 1.5 * x[:, 0] - x[:, 1] * x[:, 2]
        y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
        return x, y, np.ones(n, dtype=np.float32)

    def test_gbt_beats_stump_and_improves_with_rounds(self, binary_data):
        x, y, w = binary_data
        l5 = _logloss(GradientBoostedTreesClassifier(num_rounds=5, max_depth=3)
                      ._fit_arrays(x, y, w).predict_column(Column.vector(x)).score, y)
        l50 = _logloss(GradientBoostedTreesClassifier(num_rounds=50, max_depth=3)
                       ._fit_arrays(x, y, w).predict_column(Column.vector(x)).score, y)
        assert l50 < l5 < _logloss(np.full_like(y, y.mean()), y)

    def test_rf_probabilities_calibrated(self, binary_data):
        x, y, w = binary_data
        m = RandomForestClassifier(num_trees=30, max_depth=6)._fit_arrays(x, y, w)
        p = m.predict_column(Column.vector(x))
        assert 0.0 <= p.prob.min() and p.prob.max() <= 1.0
        np.testing.assert_allclose(p.prob.sum(axis=1), 1.0, atol=1e-6)
        assert ((p.score > 0.5) == y).mean() > 0.75

    def test_decision_tree_deterministic(self, binary_data):
        x, y, w = binary_data
        p1 = DecisionTreeClassifier(max_depth=4)._fit_arrays(x, y, w) \
            .predict_column(Column.vector(x)).score
        p2 = DecisionTreeClassifier(max_depth=4)._fit_arrays(x, y, w) \
            .predict_column(Column.vector(x)).score
        np.testing.assert_array_equal(p1, p2)

    def test_regressors_fit_signal(self):
        rng = np.random.default_rng(3)
        n = 1200
        x = rng.normal(size=(n, 5)).astype(np.float32)
        y = (2 * x[:, 0] + x[:, 1] ** 2 + 0.1 * rng.normal(size=n)).astype(np.float32)
        w = np.ones(n, dtype=np.float32)
        for est in (GradientBoostedTreesRegressor(num_rounds=40, max_depth=4, eta=0.2),
                    RandomForestRegressor(num_trees=25, max_depth=8, feature_subset="all"),
                    DecisionTreeRegressor(max_depth=8)):
            pred = est._fit_arrays(x, y, w).predict_column(Column.vector(x)).pred
            r2 = 1 - ((pred - y) ** 2).mean() / y.var()
            assert r2 > 0.8, f"{type(est).__name__} r2={r2}"

    def test_feature_importances(self, binary_data):
        x, y, w = binary_data
        m = GradientBoostedTreesClassifier(num_rounds=20, max_depth=3) \
            ._fit_arrays(x, y, w)
        imp = m.feature_importances(x.shape[1])
        assert imp.shape == (x.shape[1],)
        assert abs(imp.sum() - 1.0) < 1e-9
        # signal features (x0, x1, x2) dominate pure-noise features
        assert imp[:3].sum() > imp[3:].sum()


class TestShardedParity:
    def test_row_sharded_fit_matches_single_device(self):
        """Histogram psum over the data axis must not change the fitted trees."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from transmogrifai_tpu.models.trees import _fit_gbt
        from transmogrifai_tpu.parallel.mesh import make_mesh
        rng = np.random.default_rng(4)
        n, d = 512, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        binned, _ = quantile_bin(x, 16)
        w = np.ones(n, dtype=np.float32)

        args = dict(n_rounds=5, max_depth=3, n_bins=16, objective="binary:logistic",
                    eta=0.3, reg_lambda=1.0, gamma=0.0, min_child_weight=1.0,
                    base_score=0.0)
        _, t_single = _fit_gbt(jnp.asarray(binned), jnp.asarray(y), jnp.asarray(w),
                               **args)

        mesh = make_mesh()
        shard = NamedSharding(mesh, P("data"))
        _, t_shard = _fit_gbt(
            jax.device_put(binned, NamedSharding(mesh, P("data", None))),
            jax.device_put(y, shard), jax.device_put(w, shard), **args)
        np.testing.assert_allclose(np.asarray(t_single.value),
                                   np.asarray(t_shard.value), atol=1e-4)
        np.testing.assert_array_equal(np.asarray(t_single.feat),
                                      np.asarray(t_shard.feat))


class TestWorkflowIntegration:
    def test_selector_with_trees_and_serde(self, tmp_path):
        from transmogrifai_tpu import (
            BinaryClassificationModelSelector, FeatureBuilder, Workflow,
            WorkflowModel, transmogrify,
        )
        import pandas as pd

        rng = np.random.default_rng(5)
        n = 400
        a = rng.normal(size=n)
        b = rng.choice(["x", "y", "z"], n)
        y = ((a > 0) & (b != "z")).astype(int)
        df = pd.DataFrame({"a": a, "b": b, "label": y})
        feats, ds = FeatureBuilder.from_dataframe(df, response="label")
        fmap = {f.name: f for f in feats}
        vec = transmogrify([fmap["a"], fmap["b"]])
        models = [(GradientBoostedTreesClassifier(n_bins=16),
                   [{"num_rounds": 10, "max_depth": 3}]),
                  (XGBoostClassifier(n_bins=16),
                   [{"num_rounds": 5, "max_depth": 2, "eta": 0.5}])]
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2, models=models, seed=0)
        pred = sel.set_input(fmap["label"], vec).get_output()
        model = Workflow().set_result_features(fmap["label"], pred) \
            .set_input_dataset(ds).train()
        scored = model.score(ds)
        s = scored[pred.name].score
        assert ((s > 0.5) == y).mean() > 0.8

        model.save(str(tmp_path / "m"))
        m2 = WorkflowModel.load(str(tmp_path / "m"))
        s2 = m2.score(ds)[pred.name].score
        np.testing.assert_allclose(s, s2, atol=1e-6)
