"""Tree-ensemble tests: exact split math, missing-value routing, regularization,
sharded-parity, and workflow/serde integration (reference test strategy SURVEY §4 —
OpEstimatorSpec behavior: fit → model → transform parity → serde round-trip)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from transmogrifai_tpu.data.dataset import Column
from transmogrifai_tpu.models.trees import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    GradientBoostedTreesClassifier,
    GradientBoostedTreesRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
    XGBoostClassifier,
    quantile_bin,
)


def _logloss(p, y):
    p = np.clip(p, 1e-9, 1 - 1e-9)
    return -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()


class TestQuantileBin:
    def test_bins_cover_range_and_missing(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(500, 3)).astype(np.float32)
        x[::7, 1] = np.nan
        binned, edges = quantile_bin(x, n_bins=16)
        assert binned.shape == (500, 3)
        assert edges.shape == (3, 15)
        assert (binned[::7, 1] == 16).all()          # missing -> reserved bin
        ok = ~np.isnan(x)
        assert binned[ok].max() < 16 and binned[ok].min() >= 0
        # monotone: larger value -> same or larger bin
        order = np.argsort(x[:, 0])
        assert (np.diff(binned[order, 0]) >= 0).all()

    def test_constant_column(self):
        x = np.ones((50, 1), dtype=np.float32)
        binned, _ = quantile_bin(x, n_bins=8)
        assert len(np.unique(binned)) == 1


class TestExactTreeMath:
    def test_single_split_leaf_values(self):
        """Hand-computed XGBoost math: depth-1 regression tree, lambda=0, eta=1."""
        x = np.array([[1.0], [2.0], [10.0], [11.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0], dtype=np.float32)
        est = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=0.0,
            min_child_weight=0.0, n_bins=4)
        m = est._fit_arrays(x, y, np.ones(4, dtype=np.float32))
        # base = 0.5; grads = 0.5-y; leaf values -G/H = ±0.5 -> exact predictions
        pred = m.predict_column(Column.vector(x)).pred
        np.testing.assert_allclose(pred, y, atol=1e-6)

    def test_lambda_shrinks_leaves(self):
        x = np.array([[1.0], [2.0], [10.0], [11.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0], dtype=np.float32)
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=2.0,
            min_child_weight=0.0, n_bins=4,
        )._fit_arrays(x, y, np.ones(4, dtype=np.float32))
        pred = m.predict_column(Column.vector(x)).pred
        # leaf value = -G/(H+2) = ±0.25 -> predictions pulled toward base 0.5
        np.testing.assert_allclose(pred, [0.25, 0.25, 0.75, 0.75], atol=1e-6)

    def test_gamma_prunes_to_stump(self):
        x = np.array([[1.0], [2.0], [10.0], [11.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0], dtype=np.float32)
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=3, eta=1.0, reg_lambda=0.0, gamma=1e6,
            n_bins=4)._fit_arrays(x, y, np.ones(4, dtype=np.float32))
        pred = m.predict_column(Column.vector(x)).pred
        np.testing.assert_allclose(pred, 0.5, atol=1e-6)  # no split: base score

    def test_sample_weights_shift_split(self):
        """Zero-weight rows must not influence fitting at all."""
        x = np.array([[1.0], [2.0], [10.0], [11.0], [100.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0, 5.0], dtype=np.float32)
        w = np.array([1, 1, 1, 1, 0], dtype=np.float32)
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=0.0,
            min_child_weight=0.0, n_bins=8)._fit_arrays(x, y, w)
        pred = m.predict_column(Column.vector(x[:4])).pred
        np.testing.assert_allclose(pred, y[:4], atol=1e-6)


class TestMissingValues:
    def test_learned_default_direction(self):
        """Missing values correlated with the positive class must route there."""
        rng = np.random.default_rng(1)
        n = 1000
        x = rng.normal(size=(n, 2)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        miss = rng.random(n) < 0.3
        # make x0 missing mostly on the POSITIVE side
        miss &= y == 1
        x[miss, 0] = np.nan
        m = GradientBoostedTreesClassifier(
            num_rounds=10, max_depth=3, eta=0.5)._fit_arrays(
            x, y, np.ones(n, dtype=np.float32))
        score = m.predict_column(Column.vector(x)).score
        assert score[miss].mean() > 0.7  # missing rows recognized as positive


class TestEnsembles:
    @pytest.fixture(scope="class")
    def binary_data(self):
        rng = np.random.default_rng(2)
        n, d = 1500, 6
        x = rng.normal(size=(n, d)).astype(np.float32)
        logit = 1.5 * x[:, 0] - x[:, 1] * x[:, 2]
        y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
        return x, y, np.ones(n, dtype=np.float32)

    def test_gbt_beats_stump_and_improves_with_rounds(self, binary_data):
        x, y, w = binary_data
        l5 = _logloss(GradientBoostedTreesClassifier(num_rounds=5, max_depth=3)
                      ._fit_arrays(x, y, w).predict_column(Column.vector(x)).score, y)
        l50 = _logloss(GradientBoostedTreesClassifier(num_rounds=50, max_depth=3)
                       ._fit_arrays(x, y, w).predict_column(Column.vector(x)).score, y)
        assert l50 < l5 < _logloss(np.full_like(y, y.mean()), y)

    def test_rf_probabilities_calibrated(self, binary_data):
        x, y, w = binary_data
        m = RandomForestClassifier(num_trees=30, max_depth=6)._fit_arrays(x, y, w)
        p = m.predict_column(Column.vector(x))
        assert 0.0 <= p.prob.min() and p.prob.max() <= 1.0
        np.testing.assert_allclose(p.prob.sum(axis=1), 1.0, atol=1e-6)
        assert ((p.score > 0.5) == y).mean() > 0.75

    def test_decision_tree_deterministic(self, binary_data):
        x, y, w = binary_data
        p1 = DecisionTreeClassifier(max_depth=4)._fit_arrays(x, y, w) \
            .predict_column(Column.vector(x)).score
        p2 = DecisionTreeClassifier(max_depth=4)._fit_arrays(x, y, w) \
            .predict_column(Column.vector(x)).score
        np.testing.assert_array_equal(p1, p2)

    def test_regressors_fit_signal(self):
        rng = np.random.default_rng(3)
        n = 1200
        x = rng.normal(size=(n, 5)).astype(np.float32)
        y = (2 * x[:, 0] + x[:, 1] ** 2 + 0.1 * rng.normal(size=n)).astype(np.float32)
        w = np.ones(n, dtype=np.float32)
        for est in (GradientBoostedTreesRegressor(num_rounds=40, max_depth=4, eta=0.2),
                    RandomForestRegressor(num_trees=25, max_depth=8, feature_subset="all"),
                    DecisionTreeRegressor(max_depth=8)):
            pred = est._fit_arrays(x, y, w).predict_column(Column.vector(x)).pred
            r2 = 1 - ((pred - y) ** 2).mean() / y.var()
            assert r2 > 0.8, f"{type(est).__name__} r2={r2}"

    def test_feature_importances(self, binary_data):
        x, y, w = binary_data
        m = GradientBoostedTreesClassifier(num_rounds=20, max_depth=3) \
            ._fit_arrays(x, y, w)
        imp = m.feature_importances(x.shape[1])
        assert imp.shape == (x.shape[1],)
        assert abs(imp.sum() - 1.0) < 1e-9
        # signal features (x0, x1, x2) dominate pure-noise features
        assert imp[:3].sum() > imp[3:].sum()


class TestShardedParity:
    def test_row_sharded_fit_matches_single_device(self):
        """Histogram psum over the data axis must not change the fitted trees."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from transmogrifai_tpu.models.trees import _fit_gbt
        from transmogrifai_tpu.parallel.mesh import make_mesh
        rng = np.random.default_rng(4)
        n, d = 512, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        binned, _ = quantile_bin(x, 16)
        w = np.ones(n, dtype=np.float32)

        args = dict(n_rounds=5, max_depth=3, n_bins=16, objective="binary:logistic",
                    num_class=1, subsample=1.0, colsample_bytree=1.0,
                    colsample_bylevel=1.0, eta=0.3, reg_lambda=1.0, alpha=0.0,
                    gamma=0.0, min_child_weight=1.0, scale_pos_weight=1.0,
                    max_delta_step=0.0, base_score=jnp.zeros(1))
        key = jax.random.PRNGKey(0)
        _, t_single = _fit_gbt(jnp.asarray(binned), jnp.asarray(y), jnp.asarray(w),
                               key, **args)

        mesh = make_mesh()
        shard = NamedSharding(mesh, P("data"))
        _, t_shard = _fit_gbt(
            jax.device_put(binned, NamedSharding(mesh, P("data", None))),
            jax.device_put(y, shard), jax.device_put(w, shard), key, **args)
        np.testing.assert_allclose(np.asarray(t_single.value),
                                   np.asarray(t_shard.value), atol=1e-4)
        np.testing.assert_array_equal(np.asarray(t_single.feat),
                                      np.asarray(t_shard.feat))


class TestWorkflowIntegration:
    def test_selector_with_trees_and_serde(self, tmp_path):
        from transmogrifai_tpu import (
            BinaryClassificationModelSelector, FeatureBuilder, Workflow,
            WorkflowModel, transmogrify,
        )
        import pandas as pd

        rng = np.random.default_rng(5)
        n = 400
        a = rng.normal(size=n)
        b = rng.choice(["x", "y", "z"], n)
        y = ((a > 0) & (b != "z")).astype(int)
        df = pd.DataFrame({"a": a, "b": b, "label": y})
        feats, ds = FeatureBuilder.from_dataframe(df, response="label")
        fmap = {f.name: f for f in feats}
        vec = transmogrify([fmap["a"], fmap["b"]])
        models = [(GradientBoostedTreesClassifier(n_bins=16),
                   [{"num_rounds": 10, "max_depth": 3}]),
                  (XGBoostClassifier(n_bins=16),
                   [{"num_rounds": 5, "max_depth": 2, "eta": 0.5}])]
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2, models=models, seed=0)
        pred = sel.set_input(fmap["label"], vec).get_output()
        model = Workflow().set_result_features(fmap["label"], pred) \
            .set_input_dataset(ds).train()
        scored = model.score(ds)
        s = scored[pred.name].score
        assert ((s > 0.5) == y).mean() > 0.8

        model.save(str(tmp_path / "m"))
        m2 = WorkflowModel.load(str(tmp_path / "m"))
        s2 = m2.score(ds)[pred.name].score
        np.testing.assert_allclose(s, s2, atol=1e-6)


class TestMulticlass:
    """VERDICT r1 #1: K-class trees with (n, K) probabilities and finite CV."""

    @pytest.fixture(scope="class")
    def tri_data(self):
        rng = np.random.default_rng(7)
        n = 900
        x = rng.normal(size=(n, 4)).astype(np.float32)
        y = np.select([x[:, 0] + x[:, 1] > 0.7, x[:, 0] - x[:, 1] > 0.2],
                      [2.0, 1.0], 0.0).astype(np.float32)
        return x, y, np.ones(n, dtype=np.float32)

    def test_rf_three_class_probs(self, tri_data):
        x, y, w = tri_data
        m = RandomForestClassifier(num_trees=30, max_depth=6)._fit_arrays(x, y, w)
        p = m.predict_column(Column.vector(x))
        assert p.prob.shape == (len(y), 3)
        np.testing.assert_allclose(p.prob.sum(axis=1), 1.0, atol=1e-6)
        assert (p.pred == y).mean() > 0.8

    def test_decision_tree_three_class(self, tri_data):
        x, y, w = tri_data
        m = DecisionTreeClassifier(max_depth=6)._fit_arrays(x, y, w)
        p = m.predict_column(Column.vector(x))
        assert p.prob.shape == (len(y), 3)
        assert (p.pred == y).mean() > 0.75

    def test_gbt_softmax_three_class(self, tri_data):
        x, y, w = tri_data
        m = GradientBoostedTreesClassifier(
            num_rounds=30, max_depth=3, eta=0.3)._fit_arrays(x, y, w)
        p = m.predict_column(Column.vector(x))
        assert p.prob.shape == (len(y), 3)
        np.testing.assert_allclose(p.prob.sum(axis=1), 1.0, atol=1e-6)
        assert (p.pred == y).mean() > 0.85

    def test_multiclass_cv_finite_all_folds(self, tri_data):
        """RF inside multiclass CV must evaluate finite on every fold (the r1 bug:
        every fold NaN'd and RF was silently excluded)."""
        from transmogrifai_tpu.evaluators.base import MultiClassificationEvaluator
        from transmogrifai_tpu.models.tuning import CrossValidator

        x, y, w = tri_data
        cv = CrossValidator(MultiClassificationEvaluator("error"), num_folds=3, seed=0)
        tw, vw = cv.fold_weights(y, w)
        for est in (RandomForestClassifier(num_trees=20, max_depth=4),
                    DecisionTreeClassifier(max_depth=4),
                    GradientBoostedTreesClassifier(num_rounds=10, max_depth=3)):
            scores = est.cv_sweep(x, y, tw, vw, [{}], cv.evaluator.metric_fn())
            assert np.isfinite(scores).all(), type(est).__name__

    @pytest.mark.slow  # full multiclass selector competition (~30s);
    # per-family multiclass CV finiteness stays tier-1 above
    def test_multiclass_selector_competes(self, tri_data):
        """≥3 model families must produce finite CV metrics in the multiclass
        selector (VERDICT r1 #1 done-criterion)."""
        from transmogrifai_tpu.models.selector import MultiClassificationModelSelector
        from transmogrifai_tpu.models.tuning import CrossValidator

        x, y, w = tri_data
        sel = MultiClassificationModelSelector.with_cross_validation(num_folds=3)
        result = sel.validator.validate(sel.models, x, y, w)
        finite_families = {
            ev.model_name for ev in result.evaluations
            if all(np.isfinite(v) for v in ev.metric_values)
        }
        assert len(finite_families) >= 3, finite_families


class TestXGBoostParams:
    """VERDICT r1 #3: full XGBoost4J param surface (XGBoostParams.scala:1-111)."""

    def _stump_data(self):
        x = np.array([[1.0], [2.0], [10.0], [11.0]], dtype=np.float32)
        y = np.array([0.0, 0.0, 1.0, 1.0], dtype=np.float32)
        return x, y, np.ones(4, dtype=np.float32)

    def test_alpha_soft_thresholds_leaves(self):
        """Exact XGBoost L1 math: leaf = -sign(G)max(|G|-alpha,0)/(H+lambda)."""
        x, y, w = self._stump_data()
        # depth-1 regression stump, base=0.5, G_left=1.0, G_right=-1.0, H=2
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=0.0, alpha=0.5,
            min_child_weight=0.0, n_bins=4)._fit_arrays(x, y, w)
        pred = m.predict_column(Column.vector(x)).pred
        # soft-thresholded G: ±0.5 -> leaf ∓0.25 -> predictions 0.25/0.75
        np.testing.assert_allclose(pred, [0.25, 0.25, 0.75, 0.75], atol=1e-6)

    def test_alpha_large_kills_all_leaves(self):
        x, y, w = self._stump_data()
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=0.0, alpha=10.0,
            min_child_weight=0.0, n_bins=4)._fit_arrays(x, y, w)
        np.testing.assert_allclose(m.predict_column(Column.vector(x)).pred, 0.5,
                                   atol=1e-6)

    def test_max_delta_step_clips_leaves(self):
        x, y, w = self._stump_data()
        m = GradientBoostedTreesRegressor(
            num_rounds=1, max_depth=1, eta=1.0, reg_lambda=0.0,
            max_delta_step=0.1, min_child_weight=0.0, n_bins=4)._fit_arrays(x, y, w)
        pred = m.predict_column(Column.vector(x)).pred
        np.testing.assert_allclose(pred, [0.4, 0.4, 0.6, 0.6], atol=1e-6)

    def test_scale_pos_weight_equals_explicit_weights(self):
        """scale_pos_weight=s must reproduce fitting with w*=s on positive rows."""
        rng = np.random.default_rng(11)
        n = 600
        x = rng.normal(size=(n, 3)).astype(np.float32)
        y = (x[:, 0] + 0.3 * rng.normal(size=n) > 1.0).astype(np.float32)
        w = np.ones(n, dtype=np.float32)
        s = 3.0
        a = GradientBoostedTreesClassifier(
            num_rounds=5, max_depth=3, scale_pos_weight=s)._fit_arrays(x, y, w)
        b = GradientBoostedTreesClassifier(
            num_rounds=5, max_depth=3)._fit_arrays(
                x, y, np.where(y == 1.0, s, 1.0).astype(np.float32))
        # same splits and leaves up to base-score difference in the margin
        np.testing.assert_array_equal(a.trees["feat"], b.trees["feat"])
        np.testing.assert_allclose(a.trees["value"], b.trees["value"], atol=2e-3)

    def test_subsample_deterministic_and_regularizes(self):
        rng = np.random.default_rng(12)
        n = 800
        x = rng.normal(size=(n, 5)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        w = np.ones(n, dtype=np.float32)
        kw = dict(num_rounds=10, max_depth=3, subsample=0.5, seed=7)
        p1 = GradientBoostedTreesClassifier(**kw)._fit_arrays(x, y, w) \
            .predict_column(Column.vector(x)).score
        p2 = GradientBoostedTreesClassifier(**kw)._fit_arrays(x, y, w) \
            .predict_column(Column.vector(x)).score
        np.testing.assert_array_equal(p1, p2)  # same seed -> same rows sampled
        assert ((p1 > 0.5) == y).mean() > 0.9  # still learns the signal

    def test_colsample_bytree_restricts_features(self):
        """With d=4 and colsample_bytree=0.25 each tree sees exactly one feature."""
        rng = np.random.default_rng(13)
        n = 500
        x = rng.normal(size=(n, 4)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        w = np.ones(n, dtype=np.float32)
        m = GradientBoostedTreesClassifier(
            num_rounds=8, max_depth=2, colsample_bytree=0.25, seed=3,
        )._fit_arrays(x, y, w)
        feats = np.asarray(m.trees["feat"])      # (rounds, m)
        leaves = np.asarray(m.trees["is_leaf"])
        for r in range(feats.shape[0]):
            used = set(feats[r][~leaves[r]].tolist())
            assert len(used) <= 1, f"round {r} split on {used}"

    def test_colsample_bylevel_restricts_per_level(self):
        rng = np.random.default_rng(14)
        n = 500
        x = rng.normal(size=(n, 4)).astype(np.float32)
        y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
        w = np.ones(n, dtype=np.float32)
        m = GradientBoostedTreesClassifier(
            num_rounds=4, max_depth=3, colsample_bylevel=0.25, seed=5,
        )._fit_arrays(x, y, w)
        feats = np.asarray(m.trees["feat"])
        leaves = np.asarray(m.trees["is_leaf"])
        # per round and per level at most one distinct split feature
        for r in range(feats.shape[0]):
            for depth in range(3):
                first, cnt = 2 ** depth - 1, 2 ** depth
                lvl = slice(first, first + cnt)
                used = set(feats[r][lvl][~leaves[r][lvl]].tolist())
                assert len(used) <= 1, (r, depth, used)

    def test_num_class_param_respected(self):
        x = np.array([[0.0], [1.0], [2.0]], dtype=np.float32)
        y = np.array([0.0, 1.0, 2.0], dtype=np.float32)
        m = GradientBoostedTreesClassifier(
            num_rounds=2, max_depth=1, num_class=5, n_bins=4,
        )._fit_arrays(x, y, np.ones(3, dtype=np.float32))
        assert m.predict_column(Column.vector(x)).prob.shape == (3, 5)


class TestFoldVmappedSweep:
    """VERDICT r1 #2: tree CV runs folds in one vmapped program per grid."""

    def test_gbt_sweep_matches_sequential(self):
        from transmogrifai_tpu.evaluators import metrics as M

        rng = np.random.default_rng(21)
        n = 400
        x = rng.normal(size=(n, 4)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        w = np.ones(n, dtype=np.float32)
        fold = rng.permutation(n) % 3
        tw = np.stack([(fold != f) * w for f in range(3)]).astype(np.float32)
        vw = np.stack([(fold == f) * w for f in range(3)]).astype(np.float32)
        est = GradientBoostedTreesClassifier(num_rounds=5, max_depth=2, n_bins=16)
        grids = [{"max_depth": 2}, {"max_depth": 3}]
        swept = est.cv_sweep(x, y, tw, vw, grids, M.METRICS_BINARY["auPR"])
        assert swept.shape == (2, 3)
        # sequential reference path: per-(grid, fold) fit + host-side metric
        for gi, grid in enumerate(grids):
            for f in range(3):
                m = est.copy().set_params(**grid)._fit_arrays(x, y, tw[f])
                s = m.predict_column(Column.vector(x)).score
                ref = float(M.METRICS_BINARY["auPR"](
                    jnp.asarray(s, jnp.float32), jnp.asarray(y), jnp.asarray(vw[f])))
                np.testing.assert_allclose(swept[gi, f], ref, atol=1e-4)

    def test_forest_sweep_matches_sequential(self):
        from transmogrifai_tpu.evaluators import metrics as M

        rng = np.random.default_rng(22)
        n = 300
        x = rng.normal(size=(n, 3)).astype(np.float32)
        y = (2.0 * x[:, 0] + rng.normal(size=n) * 0.1).astype(np.float32)
        w = np.ones(n, dtype=np.float32)
        fold = rng.permutation(n) % 2
        tw = np.stack([(fold != f) * w for f in range(2)]).astype(np.float32)
        vw = np.stack([(fold == f) * w for f in range(2)]).astype(np.float32)
        est = RandomForestRegressor(num_trees=10, max_depth=4, n_bins=16)
        swept = est.cv_sweep(x, y, tw, vw, [{}], M.METRICS_REGRESSION["rmse"])
        for f in range(2):
            m = est._fit_arrays(x, y, tw[f])
            pred = m.predict_column(Column.vector(x)).pred
            ref = float(M.METRICS_REGRESSION["rmse"](
                jnp.asarray(pred, jnp.float32), jnp.asarray(y), jnp.asarray(vw[f])))
            np.testing.assert_allclose(swept[0, f], ref, atol=1e-4)


class TestChunkedHistograms:
    """The row-chunked histogram path must produce identical trees to the
    unchunked path (it only reorders an exact sum)."""

    def test_chunked_equals_unchunked(self, monkeypatch):
        from transmogrifai_tpu.models import trees as T

        rng = np.random.default_rng(21)
        n, d = 600, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (x[:, 0] + 0.5 * x[:, 1] + rng.normal(scale=0.3, size=n) > 0
             ).astype(np.float64)

        def fit_probs():
            est = GradientBoostedTreesClassifier(num_rounds=5, max_depth=3)
            model = est._fit_arrays(x, y, np.ones(n, np.float32))
            return np.asarray(model.predict_column(Column.vector(x)).prob)

        base = fit_probs()  # n=600 < 2*CHUNK -> unchunked
        monkeypatch.setattr(T, "_HIST_CHUNK", 128)  # force chunked (600 > 256)
        # the jitted fit caches the unchunked trace (same shapes/statics);
        # drop it so the retrace actually reads the patched chunk size
        jax.clear_caches()
        chunked = fit_probs()
        jax.clear_caches()  # don't leak the tiny-chunk trace to other tests
        np.testing.assert_allclose(base, chunked, rtol=1e-5, atol=1e-6)

    def test_chunked_cv_sweep_finite(self, monkeypatch):
        from transmogrifai_tpu.models import trees as T

        monkeypatch.setattr(T, "_HIST_CHUNK", 128)
        rng = np.random.default_rng(22)
        n, d = 700, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float64)
        folds = rng.integers(0, 3, n)
        train_w = np.stack([(folds != f).astype(np.float32) for f in range(3)])
        val_w = np.stack([(folds == f).astype(np.float32) for f in range(3)])

        est = RandomForestClassifier(num_trees=5, max_depth=3)

        def metric_fn(payload, y_true, w):
            import jax.numpy as jnp
            pred = (payload > 0.5).astype(jnp.float32)
            return (w * (pred == y_true)).sum() / jnp.maximum(w.sum(), 1e-12)

        results = est.cv_sweep(x, y, train_w, val_w,
                               [{"num_trees": 5, "max_depth": 3}], metric_fn)
        vals = np.asarray(results[0])
        assert vals.shape == (3,)
        assert np.isfinite(vals).all()
        assert vals.mean() > 0.7


class TestBf16Histograms:
    """The TPU numeric path feeds histogram matmuls in bfloat16 (f32 accum);
    the CPU suite runs f32, so without this the bf16 path has zero parity
    coverage (ADVICE r2).  Forcing _hist_dtype to bf16 here must keep the
    learned ensemble's predictions within a loose tolerance of the f32 trees
    — identical split structure is NOT required (near-ties may flip), but the
    fitted function must agree."""

    def _fit_probs(self, x, y, n):
        est = GradientBoostedTreesClassifier(num_rounds=10, max_depth=3,
                                             eta=0.3)
        model = est._fit_arrays(x, y, np.ones(n, np.float32))
        return np.asarray(model.predict_column(Column.vector(x)).prob[:, 1])

    def test_bf16_histograms_match_f32_predictions(self, monkeypatch):
        from transmogrifai_tpu.models import trees as T

        rng = np.random.default_rng(31)
        n, d = 800, 6
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (x[:, 0] - 0.7 * x[:, 1] + rng.normal(scale=0.4, size=n) > 0
             ).astype(np.float64)

        base = self._fit_probs(x, y, n)
        monkeypatch.setattr(T, "_hist_dtype", lambda: jnp.bfloat16)
        jax.clear_caches()
        bf16 = self._fit_probs(x, y, n)
        jax.clear_caches()
        # bf16 grad/hess rounding perturbs split gains; the fitted
        # probabilities must stay close and rank almost identically
        assert np.abs(bf16 - base).mean() < 0.02
        assert np.corrcoef(bf16, base)[0, 1] > 0.99
        acc_base = ((base > 0.5) == y).mean()
        acc_bf16 = ((bf16 > 0.5) == y).mean()
        assert abs(acc_base - acc_bf16) < 0.03

    def test_bf16_regression_large_targets(self, monkeypatch):
        """Large-magnitude regression targets (grad ~1e4) through bf16
        histograms: R^2 must survive the 8-bit mantissa (ADVICE r2 flagged
        this as the risky regime)."""
        from transmogrifai_tpu.models import trees as T

        rng = np.random.default_rng(32)
        n, d = 800, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (3e4 * x[:, 0] + 1e4 * x[:, 1]
             + rng.normal(scale=2e3, size=n)).astype(np.float64)

        def fit_pred():
            est = GradientBoostedTreesRegressor(num_rounds=20, max_depth=3,
                                                eta=0.3)
            model = est._fit_arrays(x, y, np.ones(n, np.float32))
            return np.asarray(model.predict_column(Column.vector(x)).pred)

        base = fit_pred()
        monkeypatch.setattr(T, "_hist_dtype", lambda: jnp.bfloat16)
        jax.clear_caches()
        bf16 = fit_pred()
        jax.clear_caches()
        ss_tot = ((y - y.mean()) ** 2).sum()
        r2_base = 1 - ((base - y) ** 2).sum() / ss_tot
        r2_bf16 = 1 - ((bf16 - y) ** 2).sum() / ss_tot
        assert r2_base > 0.9
        assert r2_bf16 > 0.88, f"bf16 R2 {r2_bf16} vs f32 {r2_base}"

    def test_bf16_regression_grad_1e5_near_tied_splits(self, monkeypatch):
        """VERDICT r3 #9: gradients ~1e5 with a NEAR-DUPLICATE feature so
        split gains are near-tied — the scenario where 0.4% bf16 rounding
        could flip winners.  bf16's exponent range carries the magnitude;
        the 8-bit mantissa only adds relative noise that histogram sums
        amortize, so the fitted function must stay at f32 quality with no
        gradient pre-scaling."""
        from transmogrifai_tpu.models import trees as T

        rng = np.random.default_rng(33)
        n, d = 1000, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        x[:, 2] = x[:, 0] + rng.normal(scale=1e-3, size=n)  # near-tied gains
        y = (2e5 * x[:, 0] - 1e5 * x[:, 3]
             + rng.normal(scale=5e3, size=n)).astype(np.float64)

        def fit_pred():
            est = GradientBoostedTreesRegressor(num_rounds=25, max_depth=3,
                                                eta=0.3)
            model = est._fit_arrays(x, y, np.ones(n, np.float32))
            return np.asarray(model.predict_column(Column.vector(x)).pred)

        base = fit_pred()
        monkeypatch.setattr(T, "_hist_dtype", lambda: jnp.bfloat16)
        jax.clear_caches()
        bf16 = fit_pred()
        jax.clear_caches()
        ss_tot = ((y - y.mean()) ** 2).sum()
        r2_base = 1 - ((base - y) ** 2).sum() / ss_tot
        r2_bf16 = 1 - ((bf16 - y) ** 2).sum() / ss_tot
        assert r2_base > 0.95
        assert r2_bf16 > r2_base - 0.02, f"bf16 {r2_bf16} vs f32 {r2_base}"
        # near-tied splits may flip, but the fitted functions must agree
        # to a few percent of the target's spread
        assert np.abs(bf16 - base).mean() / y.std() < 0.05


class TestHostPredictParity:
    def test_host_and_device_margins_match(self):
        """Small batches predict on host numpy; must match the device path."""
        rng = np.random.default_rng(31)
        n, d = 700, 6
        x = rng.normal(size=(n, d)).astype(np.float32)
        x[::13, 2] = np.nan
        y = (x[:, 0] > 0).astype(np.float64)
        m = GradientBoostedTreesClassifier(
            num_rounds=8, max_depth=3)._fit_arrays(x, y, np.ones(n, np.float32))
        # one call above the host threshold (device), one below (host)
        big = np.asarray(m.predict_column(Column.vector(x)).prob)
        small = np.asarray(m.predict_column(Column.vector(x[:100])).prob)
        np.testing.assert_allclose(small, big[:100], rtol=1e-6, atol=1e-9)

    def test_host_path_multiclass(self):
        rng = np.random.default_rng(32)
        n, d = 600, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = rng.integers(0, 3, n).astype(np.float64)
        m = RandomForestClassifier(
            num_trees=5, max_depth=3)._fit_arrays(x, y, np.ones(n, np.float32))
        big = np.asarray(m.predict_column(Column.vector(x)).prob)
        small = np.asarray(m.predict_column(Column.vector(x[:50])).prob)
        np.testing.assert_allclose(small, big[:50], rtol=1e-6, atol=1e-9)


class TestExternalReferenceParity:
    """The real xgboost library is not installed in this environment, so
    XGBoost-surface parity is anchored two ways: the hand-computed XGBoost-math
    unit tests above (leaf values, lambda/gamma/alpha effects, missing-value
    directions), and this quality-tolerance comparison against sklearn's
    GradientBoostingClassifier as an external implementation of the same
    algorithm family (VERDICT r1 #3 proxy justification)."""

    def test_gbt_logloss_within_tolerance_of_sklearn(self):
        from sklearn.ensemble import GradientBoostingClassifier

        rng = np.random.default_rng(41)
        n, d = 2000, 8
        x = rng.normal(size=(n, d)).astype(np.float32)
        logit = 1.2 * x[:, 0] - x[:, 1] * x[:, 2] + 0.5 * x[:, 3]
        y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)

        ours = GradientBoostedTreesClassifier(
            num_rounds=50, max_depth=3, eta=0.3)._fit_arrays(
            x, y, np.ones(n, np.float32))
        ll_ours = _logloss(ours.predict_column(Column.vector(x)).score, y)

        sk = GradientBoostingClassifier(n_estimators=50, max_depth=3,
                                        learning_rate=0.3).fit(x, y)
        ll_sk = _logloss(sk.predict_proba(x)[:, 1], y)

        # histogram binning (64 bins) vs sklearn's exact splits: allow 15%
        assert ll_ours <= ll_sk * 1.15, (ll_ours, ll_sk)

    def test_rf_accuracy_within_tolerance_of_sklearn(self):
        from sklearn.ensemble import RandomForestClassifier as SkRF

        rng = np.random.default_rng(42)
        n, d = 2000, 8
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = ((x[:, 0] + x[:, 1] > 0)).astype(np.float64)

        ours = RandomForestClassifier(num_trees=30, max_depth=6)._fit_arrays(
            x, y, np.ones(n, np.float32))
        acc_ours = (ours.predict_column(Column.vector(x)).pred == y).mean()
        sk = SkRF(n_estimators=30, max_depth=6, random_state=0).fit(x, y)
        acc_sk = (sk.predict(x) == y).mean()
        assert acc_ours >= acc_sk - 0.05, (acc_ours, acc_sk)


def test_high_resolution_bins_capability():
    """XGBoost max_bin-style resolution stays available per-estimator
    (DEFAULT_BINS is 32 for Spark-default parity; the capability surface
    reaches 256): a signal with a narrow decision boundary that 8 coarse
    bins cannot localize is recovered at n_bins=128."""
    rng = np.random.default_rng(41)
    n = 4000
    x = rng.uniform(0, 1, size=(n, 3)).astype(np.float32)
    # boundary at 0.505 inside a uniform feature: needs fine quantile edges
    y = ((x[:, 0] > 0.505) ^ (rng.random(n) < 0.02)).astype(np.float64)
    w = np.ones(n, np.float32)

    accs = {}
    for bins in (8, 128):
        est = GradientBoostedTreesClassifier(num_rounds=20, max_depth=3,
                                             n_bins=bins)
        model = est._fit_arrays(x, y, w)
        p = np.asarray(model.predict_column(Column.vector(x)).prob[:, 1])
        accs[bins] = ((p > 0.5) == y).mean()
    assert accs[128] > 0.97, accs
    assert accs[128] >= accs[8], accs
